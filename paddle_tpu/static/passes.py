"""Program-rewrite pass framework (reference:
paddle/fluid/framework/ir/pass.h:53 Pass/PassRegistry, REGISTER_PASS:317,
and the fusion passes under paddle/fluid/framework/ir/ — conv_bn_fuse,
fc_fuse, etc.).

TPU-native stance: XLA already performs elementwise/matmul fusion, so the
pass framework's job here is the part XLA can't do — substituting op
PATTERNS with hand-written Pallas kernels (the reference analog is its
fusion passes swapping subgraphs for fused CUDA ops), plus generic
cleanups (dead-op elimination).  Passes operate on the recorded Program
(static/graph.py), the ProgramDesc analog.
"""
from __future__ import annotations

from typing import Callable, Dict, List

_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    """REGISTER_PASS analog: @register_pass("fuse_linear_act")."""

    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_pass(name: str) -> Callable:
    if name not in _REGISTRY:
        raise KeyError(f"no pass named {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_passes() -> List[str]:
    return sorted(_REGISTRY)


def _verify_rewrite(program, pass_name, keep=()):
    """Post-rewrite guard: structural verification via paddle_tpu.analysis
    (pass/graph validation analog of framework/ir's Graph::Validate).
    Imported lazily — analysis imports nothing from passes, but keeping
    the dependency one-directional at import time is cheap insurance."""
    from .. import analysis

    analysis.verify_after_pass(program, pass_name,
                               fetch_list=list(keep) or None)


def apply_pass(program, name: str, **kwargs) -> int:
    """Apply one pass to every block; returns number of rewrites.

    After any rewrite the program is re-verified (def-before-use, SSA,
    dangling refs) so a buggy pass fails loudly at rewrite time instead
    of as a KeyError deep inside the executor.  Configure with
    ``paddle_tpu.analysis.set_pass_verification(enabled, strict)``.
    """
    fn = get_pass(name)
    total = 0
    for block in program.blocks:
        total += fn(block, **kwargs) or 0
    if total:
        program._version += 1
        _verify_rewrite(program, name, keep=kwargs.get("keep", ()))
    return total


def apply_build_strategy(program, passes=("fuse_linear_act",
                                          "eliminate_dead_ops"),
                         keep=()) -> int:
    """BuildStrategy-style bundle.  ``keep`` names the program's fetch
    targets; without it, eliminate_dead_ops cannot tell a fetch-producing
    terminal op from dead code, so that pass is skipped."""
    total = 0
    for p in passes:
        if p == "eliminate_dead_ops":
            if keep:
                total += apply_pass(program, p, keep=keep)
            continue
        if p == "fuse_linear_act":
            total += apply_pass(program, p, keep=keep)
            continue
        total += apply_pass(program, p)
    if total:
        _verify_rewrite(program, "+".join(passes), keep=keep)
    return total


# --------------------------------------------------------------------------
# analysis helpers
# --------------------------------------------------------------------------

def _consumers(block):
    """var name -> list of (op, input_index) reading it."""
    out = {}
    for op in block.ops:
        for i, (kind, ref) in enumerate(op.inputs):
            if kind == "var":
                out.setdefault(ref.name, []).append((op, i))
    return out


def _producer(block):
    """var name -> op producing it."""
    out = {}
    for op in block.ops:
        for o in op.outputs:
            out[o.name] = op
    return out


# --------------------------------------------------------------------------
# fuse_linear_act: linear -> {gelu,relu,silu} ==> one fused_linear op
# --------------------------------------------------------------------------

_ACT_OPS = {"gelu": "gelu", "relu": "relu", "silu": "silu", "swish": "silu"}


def _fused_linear_fn(x, w, b, *, activation):
    import jax

    if jax.default_backend() == "tpu":
        from ..kernels.fused_linear import fused_linear

        return fused_linear(x, w, b, activation=activation)
    # off-TPU the Pallas interpreter would be slow; same math via XLA
    z = x @ w
    if b is not None:
        z = z + b
    fn = {"gelu": lambda v: jax.nn.gelu(v, approximate=False),
          "relu": jax.nn.relu, "silu": jax.nn.silu}[activation]
    return fn(z).astype(x.dtype)


@register_pass("fuse_linear_act")
def fuse_linear_act(block, keep=()) -> int:
    """Fuse `linear` + single-consumer activation into one op whose TPU
    lowering is the Pallas matmul-epilogue kernel (kernels/fused_linear.py).
    Reference analog: fc_fuse_pass + fused_gemm_epilogue.  `keep` names
    fetch targets — a pre-activation that will be fetched must survive."""
    from .graph import OpDesc

    keep = set(keep)
    consumers = _consumers(block)
    rewrites = 0
    new_ops = []
    skip = set()
    for idx, op in enumerate(block.ops):
        if id(op) in skip:
            continue
        fused = None
        if op.type == "linear" and not op.writeback and op.single:
            out_name = op.outputs[0].name
            users = consumers.get(out_name, [])
            if len(users) == 1 and out_name not in keep:
                act_op, _ = users[0]
                if act_op.type in _ACT_OPS and not act_op.writeback and \
                        act_op.single and len(act_op.inputs) == 1:
                    fused = (op, act_op, _ACT_OPS[act_op.type])
        if fused is None:
            new_ops.append(op)
            continue
        lin, act_op, act_name = fused
        skip.add(id(act_op))
        import functools

        new_op = OpDesc(
            type="fused_linear",
            fn=functools.partial(_fused_linear_fn, activation=act_name),
            attrs={},
            inputs=list(lin.inputs),
            treedef=None,  # flat convention: fn(x, w, b)
            outputs=list(act_op.outputs),
            single=True,
        )
        new_ops.append(new_op)
        rewrites += 1
    if rewrites:
        # drop the skipped activation ops (they were folded)
        block.ops[:] = [op for op in new_ops]
    return rewrites


# --------------------------------------------------------------------------
# eliminate_dead_ops: remove ops no one reads (memory_optimize analog)
# --------------------------------------------------------------------------

@register_pass("eliminate_dead_ops")
def eliminate_dead_ops(block, keep=()) -> int:
    """Drop ops whose outputs are never consumed, not persistable, not
    written back, and not in `keep` (fetch targets).  Runs to fixpoint."""
    keep = set(keep)
    removed_total = 0
    while True:
        consumers = _consumers(block)
        removed = 0
        kept_ops = []
        for op in block.ops:
            dead = (
                not op.writeback
                and op.type not in ("backward", "cond", "while")
                and all(o.name not in keep
                        and not getattr(o, "persistable", False)
                        and not consumers.get(o.name)
                        for o in op.outputs))
            if dead:
                removed += 1
            else:
                kept_ops.append(op)
        block.ops[:] = kept_ops
        removed_total += removed
        if not removed:
            return removed_total


# --------------------------------------------------------------------------
# quant_aware: static-graph QAT insertion (reference:
# fluid/contrib/slim/quantization/quantization_pass.py — inserts
# fake_quantize/dequantize ops before quantizable ops in the Program).
# TPU-native: the op's lowering fn is wrapped with dynamic abs-max
# fake-quant (STE) on its tensor operands; XLA fuses the quant math into
# the surrounding program, and append_backward differentiates through
# the straight-through estimator like any other op.
# --------------------------------------------------------------------------

_QUANTIZABLE_ARGS = {"matmul": (0, 1), "linear": (0, 1), "conv2d": (0, 1),
                     "fused_linear": (0, 1), "mul": (0, 1)}


@register_pass("quant_aware")
def quant_aware(block, keep=(), bits=8) -> int:
    """Wrap matmul/linear/conv2d ops with fake-quant on both operands
    (activation AND weight), the static QAT rewrite.  Returns the number
    of ops instrumented; idempotent via op.extra['quantized']."""
    import jax.numpy as jnp

    from ..quantization import _ste_quant

    qmax = float(2 ** (bits - 1) - 1)

    def _fq(v):
        return _ste_quant(v, jnp.max(jnp.abs(v)), qmax)

    count = 0
    for op in block.ops:
        idxs = _QUANTIZABLE_ARGS.get(op.type)
        if not idxs or op.extra.get("quantized"):
            continue
        orig = op.fn

        def wrapped(*args, __orig=orig, __idxs=idxs, **kwargs):
            args = list(args)
            for i in __idxs:
                if i < len(args) and hasattr(args[i], "dtype") and \
                        jnp.issubdtype(args[i].dtype, jnp.floating):
                    args[i] = _fq(args[i])
            return __orig(*args, **kwargs)

        op.fn = wrapped
        op.extra["quantized"] = True
        count += 1
    return count
