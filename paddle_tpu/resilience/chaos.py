"""Deterministic fault injection for resilience testing.

The reference Paddle proves its elastic story by SIGKILLing real trainer
processes (SURVEY.md §4); that is faithful but slow and non-deterministic.
Here faults are a seeded :class:`FaultPlan` — a *schedule* of injections
(NaN gradients at step S, a crash mid-checkpoint on save N, a truncated
or bit-flipped checkpoint file, a delayed or killed training step) that
instrumented code consults through module-level hooks.  The hooks are
no-ops unless a plan is ACTIVE (``with FaultPlan(...):``), so production
paths pay one ``is None`` check.

Determinism is the point: a chaos test that reproduces bit-identical
final weights across kill/resume (tests/test_resilience.py) is only
meaningful if the fault fires at exactly the same step with exactly the
same corruption every run.  All randomness (NaN positions, flipped bits)
derives from ``FaultPlan.seed``.

Instrumented sites:

- ``on_step(step)``        — training loop, once per batch (delay/kill)
- ``on_save(site)``        — checkpoint writers, mid-commit (crash)
- ``after_save(path)``     — checkpoint writers, post-commit (disk rot)
- ``maybe_fail_request(request_id)`` — serving prefill (poison request)
- ``maybe_fail_serving_step(label)`` — serving step watchdog (hung or
  failing compiled-step ATTEMPTS: delays register as watchdog stalls,
  exceptions exercise the bounded-retry path)
- ``poison_batch(step, arrays)``     — data path (NaN/Inf gradients)

``burst_prompts`` is the matching ARRIVAL generator: a seeded batch of
random prompts for overload tests, so a shedding/degradation scenario
replays identically every run.
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Union

import numpy as np

__all__ = [
    "FaultPlan",
    "ChaosError",
    "SimulatedPreemption",
    "PROCESS_KILL_EXIT_CODE",
    "active_plan",
    "on_step",
    "on_save",
    "after_save",
    "maybe_fail_request",
    "maybe_fail_serving_step",
    "poison_batch",
    "burst_prompts",
    "truncate_file",
    "bitflip_file",
]


class ChaosError(RuntimeError):
    """An injected fault (crash-mid-save, poisoned request, ...)."""


class SimulatedPreemption(ChaosError):
    """An injected kill of the training process at a scheduled step —
    catch it where the real preemption (SIGTERM) would end the run."""


_ACTIVE: Optional["FaultPlan"] = None

#: exit code used by hard process kills (``kill_hard=True``) so launchers
#: and tests can tell an injected death from a genuine crash.
PROCESS_KILL_EXIT_CODE = 43


def active_plan() -> Optional["FaultPlan"]:
    return _ACTIVE


def _process_index() -> int:
    """This process's cluster index (0 when not in a cluster) — lazy so
    single-process chaos never pulls in the distributed stack."""
    try:
        from ..distributed import bootstrap

        return bootstrap.process_index()
    except Exception:
        return 0


class FaultPlan:
    """A seeded, deterministic schedule of fault injections.

    Use as a context manager; entering activates the plan for every
    instrumented site in the process (one plan at a time — nesting
    raises, because two overlapping schedules cannot be deterministic).

    Parameters
    ----------
    seed: drives NaN positions and bit-flip offsets.
    nan_batch_steps: global steps whose batch is poisoned with NaN
        (``poison_batch``; float arrays only).
    inf_batch_steps: same, with +inf (a different non-finite pathology).
    kill_at_step: raise :class:`SimulatedPreemption` at this step's
        ``on_step`` — the in-process stand-in for SIGKILL.
    sigterm_at_step: deliver a REAL ``SIGTERM`` to this process at the
        step — exercises the checkpointer's preemption handler.
    delay_steps: {step: seconds} — sleep before the step runs.
    crash_on_save: 1-based ordinal of the ``on_save`` call that raises
        :class:`ChaosError` mid-commit (before the manifest/rename).
    corrupt_after_save: {1-based save ordinal: "truncate" | "bitflip"}
        — silently damage one committed checkpoint file on disk, the
        bit-rot / torn-write case integrity checking must catch.
    fail_request_ids: serving request ids whose prefill raises
        :class:`ChaosError` (the poison-request case).
    step_delay_s: injected latency into serving compiled-step ATTEMPTS
        (``maybe_fail_serving_step``, 1-based attempt ordinal counted
        across prefill+decode, retries included).  Either a plain float
        — every attempt sleeps that long, the sustained-slowdown case —
        or ``{ordinal: seconds}`` for targeted hangs.  The sleep lands
        inside the engine watchdog's timed window, so a big enough
        delay IS a detected stall.
    fail_step_at: 1-based serving-step attempt ordinals that raise
        :class:`ChaosError` instead of running — the transient device
        failure the watchdog's bounded retry must absorb (consecutive
        ordinals exhaust the retries and quarantine the engine).
    kill_process_at: ``{step: process_index}`` — process-scoped kill:
        at ``on_step(step)``, ONLY the process whose cluster index
        (``distributed.bootstrap.process_index()``) matches dies; its
        peers keep running until the fleet supervisor notices.  With
        ``kill_hard=False`` (default) the death is a raised
        :class:`SimulatedPreemption`; with ``kill_hard=True`` it is
        ``os._exit`` — no cleanup, no atexit, the honest SIGKILL stand-in
        for multi-process crash tests.
    kill_save_site: substring matched against checkpoint ``on_save``
        sites; the first in-scope matching call (see
        ``kill_save_site_ordinal``) dies mid-save.  The sharded
        checkpointer's sites make every protocol window targetable:
        ``"resilience::shard:"`` (mid-shard-write, torn shard file),
        ``"resilience::shards_done"`` (between shards and manifest),
        ``"resilience::manifest"`` (before the manifest lands),
        ``"resilience::commit"`` (manifest written, rename pending).
    save_fault_process: scope ``kill_save_site`` to one cluster process
        index (``None`` = any process).
    kill_save_site_ordinal: 1-based ordinal among in-scope matching
        ``on_save`` calls that actually dies (default: the first).
    kill_hard: make ``kill_process_at`` / ``kill_save_site`` deaths
        ``os._exit(PROCESS_KILL_EXIT_CODE)`` instead of raised
        exceptions.
    step_fault_scope: when set, ONLY serving-step attempts whose label
        contains this substring are counted and faulted — the others
        pass through untouched (their ordinals do not advance the
        schedule).  A fleet of named replicas labels its steps
        ``serving::decode_step@<name>`` (ServingConfig(name=...)), so
        ``step_fault_scope="@replica-1"`` kills or stalls exactly one
        replica of a router while its siblings keep serving —
        deterministic replica-targeted chaos.
    """

    def __init__(self, seed: int = 0,
                 nan_batch_steps: Iterable[int] = (),
                 inf_batch_steps: Iterable[int] = (),
                 kill_at_step: Optional[int] = None,
                 sigterm_at_step: Optional[int] = None,
                 delay_steps: Optional[Dict[int, float]] = None,
                 crash_on_save: Optional[int] = None,
                 corrupt_after_save: Optional[Dict[int, str]] = None,
                 fail_request_ids: Iterable[str] = (),
                 step_delay_s: Union[None, float,
                                     Dict[int, float]] = None,
                 fail_step_at: Iterable[int] = (),
                 step_fault_scope: Optional[str] = None,
                 kill_process_at: Optional[Dict[int, int]] = None,
                 kill_save_site: Optional[str] = None,
                 save_fault_process: Optional[int] = None,
                 kill_save_site_ordinal: int = 1,
                 kill_hard: bool = False):
        self.seed = seed
        self.nan_batch_steps = frozenset(nan_batch_steps)
        self.inf_batch_steps = frozenset(inf_batch_steps)
        self.kill_at_step = kill_at_step
        self.sigterm_at_step = sigterm_at_step
        self.delay_steps = dict(delay_steps or {})
        self.crash_on_save = crash_on_save
        self.corrupt_after_save = dict(corrupt_after_save or {})
        for kind in self.corrupt_after_save.values():
            if kind not in ("truncate", "bitflip"):
                raise ValueError(f"unknown corruption kind {kind!r}")
        self.fail_request_ids = frozenset(fail_request_ids)
        self.step_delay_s = step_delay_s
        self.fail_step_at = frozenset(fail_step_at)
        self.step_fault_scope = step_fault_scope
        self.kill_process_at = dict(kill_process_at or {})
        self.kill_save_site = kill_save_site
        self.save_fault_process = save_fault_process
        self.kill_save_site_ordinal = kill_save_site_ordinal
        self.kill_hard = kill_hard
        # observability: what actually fired (tests assert on these)
        self.injected: list = []
        self._save_calls = 0
        self._save_site_hits = 0
        self._serving_step_calls = 0

    # ------------------------------------------------------------ scope
    def __enter__(self) -> "FaultPlan":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a FaultPlan is already active; chaos "
                               "schedules do not nest")
        _ACTIVE = self
        return self

    def __exit__(self, *exc):
        global _ACTIVE
        _ACTIVE = None
        return False

    # ------------------------------------------------------------ hooks
    def on_step(self, step: int):
        delay = self.delay_steps.get(step)
        if delay:
            import time

            self.injected.append(("delay", step))
            time.sleep(delay)
        if self.sigterm_at_step == step:
            import signal

            self.injected.append(("sigterm", step))
            os.kill(os.getpid(), signal.SIGTERM)
        if self.kill_at_step == step:
            self.injected.append(("kill", step))
            raise SimulatedPreemption(f"injected kill at step {step}")
        victim = self.kill_process_at.get(step)
        if victim is not None and victim == _process_index():
            self.injected.append(("kill_process", step, victim))
            self._die(f"injected process kill: step {step} "
                      f"process {victim}")

    def _die(self, reason: str):
        """A process-scoped death: hard (``os._exit``, the SIGKILL
        stand-in — no cleanup, no flushed buffers) or soft (raised
        :class:`SimulatedPreemption`)."""
        if self.kill_hard:
            import sys as _sys

            print(f"[chaos] {reason} (os._exit)", file=_sys.stderr,
                  flush=True)
            os._exit(PROCESS_KILL_EXIT_CODE)
        raise SimulatedPreemption(reason)

    def on_save(self, site: str):
        self._save_calls += 1
        if self.crash_on_save == self._save_calls:
            self.injected.append(("crash_save", site))
            raise ChaosError(
                f"injected crash during checkpoint save #{self._save_calls} "
                f"({site})")
        if self.kill_save_site is not None and self.kill_save_site in site:
            if self.save_fault_process is None \
                    or self.save_fault_process == _process_index():
                self._save_site_hits += 1
                if self._save_site_hits == self.kill_save_site_ordinal:
                    self.injected.append(("kill_save", site))
                    self._die(f"injected death mid-save at {site}")

    def after_save(self, path: str):
        kind = self.corrupt_after_save.get(self._save_calls)
        if kind is None:
            return
        victim = _largest_payload_file(path)
        if victim is None:
            return
        if kind == "truncate":
            truncate_file(victim)
        else:
            bitflip_file(victim, seed=self.seed)
        self.injected.append((kind, victim))

    def maybe_fail_request(self, request_id: str):
        if request_id in self.fail_request_ids:
            self.injected.append(("fail_request", request_id))
            raise ChaosError(f"injected prefill failure for {request_id}")

    def maybe_fail_serving_step(self, label: str):
        """One serving compiled-step ATTEMPT (prefill chunk or decode
        iteration, retries counted separately) — sleep and/or raise per
        the schedule.  Called inside the engine watchdog's monotonic
        window, so injected delays are observed as stalls.  With a
        ``step_fault_scope``, attempts outside the scope pass through
        without advancing the schedule (replica-targeted chaos)."""
        if self.step_fault_scope is not None \
                and self.step_fault_scope not in label:
            return
        self._serving_step_calls += 1
        n = self._serving_step_calls
        delay = (self.step_delay_s if isinstance(
            self.step_delay_s, (int, float))
            else (self.step_delay_s or {}).get(n))
        if delay:
            import time

            self.injected.append(("serving_delay", n, label))
            time.sleep(delay)
        if n in self.fail_step_at:
            self.injected.append(("serving_fail", n, label))
            raise ChaosError(
                f"injected serving step failure at attempt {n} ({label})")

    def poison_batch(self, step: int, arrays):
        """Return ``arrays`` (a list/tuple of numpy arrays) with NaN/Inf
        written into the float entries when ``step`` is scheduled;
        positions are seeded, so reruns poison identically."""
        bad = (np.nan if step in self.nan_batch_steps
               else np.inf if step in self.inf_batch_steps else None)
        if bad is None:
            return arrays
        rng = np.random.RandomState(self.seed * 100003 + step)
        out = []
        poisoned = False
        for a in arrays:
            a = np.asarray(a)
            if np.issubdtype(a.dtype, np.floating) and a.size:
                a = a.copy()
                flat = a.reshape(-1)
                k = max(1, flat.size // 8)
                flat[rng.choice(flat.size, size=k, replace=False)] = bad
                poisoned = True
            out.append(a)
        if poisoned:
            self.injected.append(("poison", step))
        return out


# ---------------------------------------------------------------------------
# module-level hooks (what instrumented code actually calls)
# ---------------------------------------------------------------------------

def on_step(step: int):
    if _ACTIVE is not None:
        _ACTIVE.on_step(step)


def on_save(site: str):
    if _ACTIVE is not None:
        _ACTIVE.on_save(site)


def after_save(path: str):
    if _ACTIVE is not None:
        _ACTIVE.after_save(path)


def maybe_fail_request(request_id: str):
    if _ACTIVE is not None:
        _ACTIVE.maybe_fail_request(request_id)


def maybe_fail_serving_step(label: str):
    if _ACTIVE is not None:
        _ACTIVE.maybe_fail_serving_step(label)


def burst_prompts(seed: int, n: int, min_len: int = 4,
                  max_len: int = 32, vocab: int = 256
                  ) -> List[np.ndarray]:
    """Seeded burst-arrival generator: ``n`` random int32 prompts with
    lengths uniform in ``[min_len, max_len]`` — the deterministic
    traffic spike overload tests and the overload bench replay so
    shedding-on and shedding-off see the IDENTICAL workload."""
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab,
                        size=(int(rng.randint(min_len, max_len + 1)),)
                        ).astype(np.int32)
            for _ in range(n)]


def poison_batch(step: int, arrays):
    if _ACTIVE is None:
        return arrays
    return _ACTIVE.poison_batch(step, arrays)


# ---------------------------------------------------------------------------
# disk corruption utilities (also usable directly from tests)
# ---------------------------------------------------------------------------

def _largest_payload_file(path: str) -> Optional[str]:
    """The biggest non-manifest file under ``path`` (or ``path`` itself
    when it is a file) — the state payload a torn write would hit."""
    if os.path.isfile(path):
        return path
    best, best_size = None, -1
    for root, _dirs, files in os.walk(path):
        for f in files:
            if f == "manifest.json":
                continue
            p = os.path.join(root, f)
            size = os.path.getsize(p)
            if size > best_size:
                best, best_size = p, size
    return best


def truncate_file(path: str, keep_frac: float = 0.5):
    """Truncate ``path`` to ``keep_frac`` of its size (a torn write)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, int(size * keep_frac)))


def bitflip_file(path: str, nbits: int = 8, seed: int = 0):
    """Flip ``nbits`` seeded-random bits in ``path`` (silent bit rot)."""
    size = os.path.getsize(path)
    if size == 0:
        return
    rng = np.random.RandomState(seed)
    with open(path, "r+b") as f:
        for _ in range(nbits):
            off = int(rng.randint(0, size))
            f.seek(off)
            byte = f.read(1)
            f.seek(off)
            f.write(bytes([byte[0] ^ (1 << int(rng.randint(0, 8)))]))
