# lint-tpu: disable-file=L004 -- host-side checkpoint I/O converts live
# jax buffers to numpy snapshots; new backend code belongs under core/
# ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Crash-safe checkpointing: atomic commits, integrity manifests,
valid-fallback restore, bounded async saves, preemption handling.

``distributed/checkpoint.py`` answers "how do shards move" (orbax,
mesh-independent restore); this module answers "what survives a crash".
The fault model (README "Resilience"):

- **Torn save** — the process dies mid-write.  Every checkpoint is
  staged in a hidden temp directory and committed with ONE
  ``os.rename`` (atomic on POSIX), so a partial save is invisible to
  restore and reaped by the next save.
- **Disk rot / torn read** — a committed file is truncated or
  bit-flipped later.  Each checkpoint carries a ``manifest.json`` of
  per-file sha256 digests, verified on restore.
- **Corrupt latest** — :meth:`ResilientCheckpointer.restore_latest`
  walks checkpoints newest-first and returns the newest one that
  verifies, counting the corrupt ones it skipped (zero corrupt
  restores, by construction).
- **Slow disk** — :meth:`ResilientCheckpointer.save_async` snapshots
  state to host numpy synchronously (the training loop may mutate
  weights immediately after) and writes from a worker thread behind a
  BOUNDED queue; a full queue blocks the caller (backpressure) instead
  of buffering unbounded host copies.
- **Preemption** — :meth:`install_preemption_handler` turns SIGTERM
  into a flag the training loop polls at batch boundaries
  (``ResilienceCallback`` then saves and stops); a signal handler
  cannot safely save mid-XLA-dispatch.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import shutil
import signal
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import chaos
from ..observability import registry as _obsreg

__all__ = [
    "CheckpointCorruption",
    "ResilientCheckpointer",
    "collect_state",
    "apply_state",
    "host_snapshot",
]

_MANIFEST = "manifest.json"
_FORMAT = 1


class CheckpointCorruption(RuntimeError):
    """A checkpoint failed integrity verification (missing file, bad
    manifest, sha256 mismatch, unreadable pickle)."""


# ---------------------------------------------------------------------------
# host-side state trees
# ---------------------------------------------------------------------------

def host_snapshot(tree: Any) -> Any:
    """Deep-copy a state tree to host numpy.  Live ``Tensor`` values sit
    on buffers the next compiled step may DONATE; snapshotting now is
    what makes async save and in-memory rollback sound."""
    if hasattr(tree, "numpy") and hasattr(tree, "_value"):   # Tensor
        return np.array(tree.numpy(), copy=True)
    if isinstance(tree, dict):
        return {k: host_snapshot(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        t = [host_snapshot(v) for v in tree]
        return t if isinstance(tree, list) else tuple(t)
    if isinstance(tree, np.ndarray):
        return tree.copy()
    if hasattr(tree, "shape") and hasattr(tree, "dtype"):    # jax array
        if getattr(tree, "sharding", None) is not None and \
                not getattr(tree, "is_fully_replicated", True):
            # mesh-sharded (distributed.MeshExecutor): gather the device
            # shards into one host array so the checkpoint is
            # layout-independent — restore re-shards onto whatever mesh
            # is active then
            import jax

            return np.asarray(jax.device_get(tree)).copy()
        return np.asarray(tree).copy()
    return tree


def collect_state(network=None, optimizer=None,
                  extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One host-numpy tree holding everything a resume needs.  Top-level
    keys become separate checkpoint files (each with its own digest)."""
    state: Dict[str, Any] = {}
    if network is not None:
        state["model"] = host_snapshot(network.state_dict())
    if optimizer is not None:
        state["optimizer"] = host_snapshot(optimizer.state_dict())
    for k, v in (extra or {}).items():
        state[k] = host_snapshot(v)
    return state


def apply_state(state: Dict[str, Any], network=None, optimizer=None):
    """Restore a :func:`collect_state` tree into live objects.  When a
    ``distributed.MeshExecutor`` is installed on the network, the host
    arrays are re-sharded back onto the mesh — the gathered save plus
    this re-shard is what keeps kill/resume bit-identical under SPMD."""
    if network is not None and "model" in state:
        network.set_state_dict(state["model"])
    if optimizer is not None and "optimizer" in state:
        optimizer.set_state_dict(state["optimizer"])
    executor = getattr(network, "_mesh_executor", None) \
        if network is not None else None
    if executor is not None:
        executor.reshard(network, optimizer)


# ---------------------------------------------------------------------------
# the checkpointer
# ---------------------------------------------------------------------------

def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ResilientCheckpointer:
    """Atomic, integrity-checked, preemption-aware checkpoint store.

    Layout: ``directory/step_00000012/{<key>.pkl..., manifest.json}``
    — one pickle per top-level state key, digests in the manifest, the
    whole directory committed by a single rename.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 max_pending: int = 2):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.max_pending = max_pending
        os.makedirs(self.directory, exist_ok=True)
        # counters (tests and stats() read these)
        self.saves = 0
        self.corrupt_skipped = 0
        # async machinery, started lazily
        self._queue: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._async_error: Optional[BaseException] = None
        # preemption
        self._preempted = False
        self._prev_handlers: Dict[int, Any] = {}
        self._reap_stale_tmp()

    # ------------------------------------------------------------ paths
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def steps(self) -> List[int]:
        """Committed checkpoint steps, ascending (no integrity check)."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    continue
        return sorted(out)

    def _reap_stale_tmp(self):
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # ------------------------------------------------------------- save
    def save(self, step: int, state: Dict[str, Any]) -> str:
        """Synchronous atomic save; returns the committed directory.

        Stage everything under ``.tmp-*``, fsync the payloads, write the
        manifest LAST, then commit with one rename — at no point does a
        partially-written checkpoint exist under a ``step_*`` name."""
        if not isinstance(state, dict) or not state:
            raise ValueError("state must be a non-empty dict of "
                             "{name: subtree}")
        t0 = time.perf_counter()
        self._reap_stale_tmp()
        tmp = os.path.join(self.directory,
                           f".tmp-{step}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp)
        try:
            files = {}
            for key, sub in state.items():
                fname = f"{key}.pkl"
                fpath = os.path.join(tmp, fname)
                with open(fpath, "wb") as f:
                    pickle.dump(host_snapshot(sub), f, protocol=4)
                    f.flush()
                    os.fsync(f.fileno())
                files[fname] = _sha256(fpath)
                chaos.on_save(f"resilience::write:{key}")
            manifest = {"format": _FORMAT, "step": step, "files": files}
            mpath = os.path.join(tmp, _MANIFEST)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            chaos.on_save("resilience::commit")
            final = self._step_dir(step)
            if os.path.exists(final):      # re-save of the same step
                shutil.rmtree(final)
            os.rename(tmp, final)          # THE commit point (atomic)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self.saves += 1
        if _obsreg.enabled():
            reg = _obsreg.get_registry()
            reg.counter("checkpoint_saves_total",
                        "checkpoints committed (atomic renames)").inc()
            reg.histogram("checkpoint_save_seconds",
                          "stage+fsync+commit wall time per checkpoint"
                          ).observe(time.perf_counter() - t0)
        chaos.after_save(final)
        self._gc()
        return final

    def save_async(self, step: int, state: Dict[str, Any]):
        """Snapshot ``state`` to host now, write it from the worker
        thread.  Blocks when ``max_pending`` saves are already queued —
        backpressure instead of unbounded host-memory growth.  An error
        from a previous async save re-raises here (and in ``wait``)."""
        self._raise_async_error()
        snap = host_snapshot(state)
        if self._worker is None:
            self._queue = queue.Queue(maxsize=self.max_pending)
            self._worker = threading.Thread(
                target=self._drain, name="resilient-ckpt", daemon=True)
            self._worker.start()
        self._queue.put((step, snap))      # blocks when full

    def _drain(self):
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                step, snap = item
                self.save(step, snap)
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                self._async_error = e
            finally:
                self._queue.task_done()

    def _raise_async_error(self):
        if self._async_error is not None:
            err, self._async_error = self._async_error, None
            raise err

    def wait(self):
        """Block until every queued async save is committed; re-raise
        the first async failure, if any."""
        if self._queue is not None:
            self._queue.join()
        self._raise_async_error()

    def close(self):
        if self._worker is not None:
            self._queue.join()
            self._queue.put(None)
            self._worker.join()
            self._worker = None
            self._queue = None
        self.uninstall_preemption_handler()
        self._raise_async_error()

    def _gc(self):
        keep = self.steps()
        if self.max_to_keep and len(keep) > self.max_to_keep:
            for step in keep[:-self.max_to_keep]:
                shutil.rmtree(self._step_dir(step), ignore_errors=True)

    # ---------------------------------------------------------- restore
    def _load_verified(self, step: int) -> Dict[str, Any]:
        d = self._step_dir(step)
        mpath = os.path.join(d, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruption(f"{d}: unreadable manifest ({e})")
        if manifest.get("format") != _FORMAT:
            raise CheckpointCorruption(
                f"{d}: unknown manifest format {manifest.get('format')!r}")
        state = {}
        for fname, digest in manifest.get("files", {}).items():
            fpath = os.path.join(d, fname)
            if not os.path.exists(fpath):
                raise CheckpointCorruption(f"{d}: missing file {fname}")
            actual = _sha256(fpath)
            if actual != digest:
                raise CheckpointCorruption(
                    f"{d}: sha256 mismatch for {fname} "
                    f"(manifest {digest[:12]}…, file {actual[:12]}…)")
            try:
                with open(fpath, "rb") as f:
                    state[fname[:-4]] = pickle.load(f)
            except Exception as e:  # noqa: BLE001 — any unpickle failure
                raise CheckpointCorruption(f"{d}: unreadable {fname} ({e})")
        return state

    def restore(self, step: int) -> Dict[str, Any]:
        """Load and VERIFY one checkpoint; raises
        :class:`CheckpointCorruption` instead of returning bad state."""
        return self._load_verified(step)

    def restore_latest(self) -> Tuple[Optional[int], Optional[Dict]]:
        """Newest checkpoint that passes verification, or ``(None,
        None)``.  Corrupt/torn checkpoints are skipped (and counted in
        ``corrupt_skipped``) — never silently restored."""
        for step in reversed(self.steps()):
            try:
                return step, self._load_verified(step)
            except CheckpointCorruption as e:
                self.corrupt_skipped += 1
                if _obsreg.enabled():
                    _obsreg.get_registry().counter(
                        "checkpoint_corrupt_skipped_total",
                        "corrupt checkpoints skipped during restore"
                    ).inc()
                print(f"[paddle_tpu.resilience] skipping corrupt "
                      f"checkpoint: {e}", file=sys.stderr)
        return None, None

    # ------------------------------------------------------- preemption
    def install_preemption_handler(self, signals=(signal.SIGTERM,)):
        """Latch preemption signals into a flag the training loop polls
        (``preemption_requested``) at batch boundaries.  Main thread
        only (CPython restricts ``signal.signal``)."""
        for s in signals:
            self._prev_handlers[s] = signal.signal(s, self._on_signal)

    def uninstall_preemption_handler(self):
        for s, prev in self._prev_handlers.items():
            signal.signal(s, prev)
        self._prev_handlers.clear()

    def _on_signal(self, signum, frame):
        self._preempted = True

    @property
    def preemption_requested(self) -> bool:
        return self._preempted

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        return {
            "directory": self.directory,
            "steps": self.steps(),
            "saves": self.saves,
            "corrupt_skipped": self.corrupt_skipped,
            "pending_async": self._queue.qsize() if self._queue else 0,
            "preemption_requested": self._preempted,
        }
