# lint-tpu: disable-file=L004 -- host-side checkpoint I/O converts live
# jax buffers to numpy snapshots; new backend code belongs under core/
# ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Crash-safe checkpointing: atomic commits, integrity manifests,
valid-fallback restore, bounded async saves, preemption handling.

``distributed/checkpoint.py`` answers "how do shards move" (orbax,
mesh-independent restore); this module answers "what survives a crash".
The fault model (README "Resilience"):

- **Torn save** — the process dies mid-write.  Every checkpoint is
  staged in a hidden temp directory and committed with ONE
  ``os.rename`` (atomic on POSIX), so a partial save is invisible to
  restore and reaped by the next save.
- **Disk rot / torn read** — a committed file is truncated or
  bit-flipped later.  Each checkpoint carries a ``manifest.json`` of
  per-file sha256 digests, verified on restore.
- **Corrupt latest** — :meth:`ResilientCheckpointer.restore_latest`
  walks checkpoints newest-first and returns the newest one that
  verifies, counting the corrupt ones it skipped (zero corrupt
  restores, by construction).
- **Slow disk** — :meth:`ResilientCheckpointer.save_async` snapshots
  state to host numpy synchronously (the training loop may mutate
  weights immediately after) and writes from a worker thread behind a
  BOUNDED queue; a full queue blocks the caller (backpressure) instead
  of buffering unbounded host copies.
- **Preemption** — :meth:`install_preemption_handler` turns SIGTERM
  into a flag the training loop polls at batch boundaries
  (``ResilienceCallback`` then saves and stops); a signal handler
  cannot safely save mid-XLA-dispatch.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import shutil
import signal
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import chaos
from ..observability import registry as _obsreg

__all__ = [
    "CheckpointCorruption",
    "ResilientCheckpointer",
    "ShardedHostLeaf",
    "collect_state",
    "apply_state",
    "host_snapshot",
]

_MANIFEST = "manifest.json"
_FORMAT = 1
_FORMAT_SHARDED = 2
_META_FILE = "_meta.pkl"


class CheckpointCorruption(RuntimeError):
    """A checkpoint failed integrity verification (missing file, bad
    manifest, sha256 mismatch, unreadable pickle)."""


# ---------------------------------------------------------------------------
# host-side state trees
# ---------------------------------------------------------------------------

class ShardedHostLeaf:
    """Host snapshot of a multi-process sharded ``jax.Array``: only this
    process's addressable shards plus the global metadata needed to
    reassemble (on disk, from every process's shards) or re-install (in
    memory, via ``make_array_from_single_device_arrays``).

    Under a real multi-controller runtime ``jax.device_get`` on a
    non-fully-addressable array RAISES — no process can see the remote
    shards — so the old gather-to-one-host snapshot is impossible by
    construction.  This leaf is what replaces it.
    """

    __slots__ = ("global_shape", "dtype", "shards", "sharding")

    def __init__(self, global_shape, dtype, shards, sharding=None):
        self.global_shape = tuple(global_shape)
        self.dtype = str(dtype)
        # [(index_bounds, np_data, replica_id, device)] where index_bounds
        # is ((start, stop), ...) per dim resolved against global_shape
        self.shards = shards
        self.sharding = sharding

    @classmethod
    def from_jax(cls, arr) -> "ShardedHostLeaf":
        shards = []
        for s in arr.addressable_shards:
            bounds = tuple(
                (sl.start if sl.start is not None else 0,
                 sl.stop if sl.stop is not None else dim)
                for sl, dim in zip(s.index, arr.shape))
            shards.append((bounds, np.asarray(s.data).copy(),
                           int(s.replica_id), s.device))
        return cls(arr.shape, arr.dtype, shards, arr.sharding)

    def to_jax(self):
        """Re-install onto the live devices this snapshot came from (the
        in-memory rollback path — no cross-process data needed)."""
        import jax

        arrs = [jax.device_put(data, dev)
                for (_b, data, _r, dev) in self.shards]
        return jax.make_array_from_single_device_arrays(
            self.global_shape, self.sharding, arrs)

    def owned_shards(self):
        """Shards THIS process must write: one writer per distinct index
        region globally (``replica_id == 0``)."""
        return [(bounds, data) for (bounds, data, rid, _d) in self.shards
                if rid == 0]

    def __repr__(self):
        return (f"ShardedHostLeaf(shape={self.global_shape}, "
                f"dtype={self.dtype}, local_shards={len(self.shards)})")

    def __reduce__(self):
        raise TypeError(
            "ShardedHostLeaf holds process-local device shards and is "
            "not picklable — multi-process state must go through the "
            "sharded checkpoint protocol (ResilientCheckpointer with "
            "sharded=True / a multi-process context), not a single-file "
            "pickle")


def host_snapshot(tree: Any) -> Any:
    """Deep-copy a state tree to host numpy.  Live ``Tensor`` values sit
    on buffers the next compiled step may DONATE; snapshotting now is
    what makes async save and in-memory rollback sound."""
    if hasattr(tree, "numpy") and hasattr(tree, "_value"):   # Tensor
        return host_snapshot(tree._value)
    if isinstance(tree, dict):
        return {k: host_snapshot(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        t = [host_snapshot(v) for v in tree]
        return t if isinstance(tree, list) else tuple(t)
    if isinstance(tree, np.ndarray):
        return tree.copy()
    if hasattr(tree, "shape") and hasattr(tree, "dtype"):    # jax array
        if getattr(tree, "sharding", None) is not None and \
                not getattr(tree, "is_fully_replicated", True):
            if not getattr(tree, "is_fully_addressable", True):
                # multi-process sharded: remote shards are unreachable
                # (device_get raises); snapshot the local shards — the
                # sharded save path writes them, every peer writes its
                # own, and restore reassembles the global array
                return ShardedHostLeaf.from_jax(tree)
            # mesh-sharded within one process: gather the device shards
            # into one host array so the checkpoint is
            # layout-independent — restore re-shards onto whatever mesh
            # is active then
            import jax

            return np.asarray(jax.device_get(tree)).copy()
        return np.asarray(tree).copy()
    return tree


def collect_state(network=None, optimizer=None,
                  extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One host-numpy tree holding everything a resume needs.  Top-level
    keys become separate checkpoint files (each with its own digest)."""
    state: Dict[str, Any] = {}
    if network is not None:
        state["model"] = host_snapshot(network.state_dict())
    if optimizer is not None:
        state["optimizer"] = host_snapshot(optimizer.state_dict())
    for k, v in (extra or {}).items():
        state[k] = host_snapshot(v)
    return state


def apply_state(state: Dict[str, Any], network=None, optimizer=None):
    """Restore a :func:`collect_state` tree into live objects.  When a
    ``distributed.MeshExecutor`` is installed on the network, the host
    arrays are re-sharded back onto the mesh — the gathered save plus
    this re-shard is what keeps kill/resume bit-identical under SPMD."""
    if network is not None and "model" in state:
        network.set_state_dict(_materialize(state["model"]))
    if optimizer is not None and "optimizer" in state:
        optimizer.set_state_dict(_materialize(state["optimizer"]))
    executor = getattr(network, "_mesh_executor", None) \
        if network is not None else None
    if executor is not None:
        executor.reshard(network, optimizer)


def _materialize(tree: Any) -> Any:
    """Turn :class:`ShardedHostLeaf` snapshots back into live jax arrays
    (in-memory rollback under a multi-process mesh); other leaves pass
    through untouched."""
    if isinstance(tree, ShardedHostLeaf):
        return tree.to_jax()
    if isinstance(tree, dict):
        return {k: _materialize(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        t = [_materialize(v) for v in tree]
        return t if isinstance(tree, list) else tuple(t)
    return tree


# ---------------------------------------------------------------------------
# the checkpointer
# ---------------------------------------------------------------------------

def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _flatten_state(tree: Dict[str, Any], prefix: str = ""
                   ) -> Dict[str, Any]:
    """Flatten nested dicts to ``a/b/c`` paths; non-dict containers are
    leaves (they ride in the coordinator's meta pickle)."""
    flat: Dict[str, Any] = {}
    for k, v in tree.items():
        path = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten_state(v, path))
        else:
            flat[path] = v
    return flat


def _unflatten_state(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, v in flat.items():
        parts = path.split("/")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _safe_key(path: str) -> str:
    return path.replace("/", "__")


def _shard_fname(path: str, bounds) -> str:
    idx = "_".join(f"{a}-{b}" for a, b in bounds) if bounds else "full"
    return f"{_safe_key(path)}.shard_{idx}.pkl"


def _is_shardable_array(v: Any) -> bool:
    return isinstance(v, ShardedHostLeaf) or (
        isinstance(v, np.ndarray) and v.ndim >= 1 and v.size > 0)


def _owned_shards(path: str, leaf: Any, ctx) -> List[Tuple[tuple,
                                                           np.ndarray]]:
    """The (index_bounds, data) shards THIS process writes for a leaf.

    :class:`ShardedHostLeaf`: the local device shards with
    ``replica_id == 0`` — exactly one writer per index region globally.
    Replicated host arrays (identical on every process by construction):
    deterministically partitioned on axis 0 across the cluster so the
    write bandwidth scales with hosts; arrays shorter than the cluster
    get a single writer picked by a stable hash of the param path.
    """
    if isinstance(leaf, ShardedHostLeaf):
        return [(b, d) for b, d in leaf.owned_shards()]
    arr = leaf
    full = tuple((0, d) for d in arr.shape)
    if ctx.count == 1:
        return [(full, arr)]
    if arr.shape[0] >= ctx.count:
        splits = np.array_split(np.arange(arr.shape[0]), ctx.count)
        rows = splits[ctx.index]
        lo, hi = int(rows[0]), int(rows[-1]) + 1
        bounds = ((lo, hi),) + tuple((0, d) for d in arr.shape[1:])
        return [(bounds, arr[lo:hi])]
    owner = int.from_bytes(
        hashlib.sha256(path.encode()).digest()[:4], "big") % ctx.count
    return [(full, arr)] if ctx.index == owner else []


def _mesh_metadata(process_count: int) -> Dict[str, Any]:
    """What the manifest records about the SAVING topology: axis sizes
    and ``SpecLayout`` of the live executor (when one is installed) plus
    the process count — restore-with-reshard provenance."""
    meta: Dict[str, Any] = {"process_count": int(process_count)}
    try:
        from ..distributed import executor as _exec

        ex = _exec.current_executor()
        if ex is not None:
            meta["axis_sizes"] = {str(k): int(v)
                                  for k, v in ex.mesh.shape.items()}
            layout = getattr(ex, "layout", None)
            if layout is not None:
                import dataclasses as _dc

                meta["layout"] = {k: v for k, v in
                                  _dc.asdict(layout).items()
                                  if isinstance(v, (str, int, float,
                                                    bool, type(None)))}
    except Exception:
        pass
    return meta


def _write_fsync(path: str, payload: bytes,
                 site: Optional[str] = None) -> str:
    """Write-to-unique-tmp + fsync + rename WITHIN the target dir (the
    torn-write guard for every sharded-protocol file); returns sha256.

    The chaos ``site`` fires between fsync and rename — a kill there
    leaves a fsynced ``.wip`` orphan and NO published file, the exact
    mid-write window the crash matrix targets."""
    tmp = f"{path}.wip-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    if site is not None:
        chaos.on_save(site)
    os.rename(tmp, path)
    return hashlib.sha256(payload).hexdigest()


class ResilientCheckpointer:
    """Atomic, integrity-checked, preemption-aware checkpoint store.

    Single-process layout (format 1):
    ``directory/step_00000012/{<key>.pkl..., manifest.json}`` — one
    pickle per top-level state key, digests in the manifest, the whole
    directory committed by a single rename.

    Sharded elastic layout (format 2, automatic when the process context
    spans >1 process, forceable with ``sharded=True``): every process
    writes ONLY the shards it owns into a shared staging directory
    (per-leaf shard pickles keyed by flattened param path + shard index
    bounds, sha256 per file, computed by the writing process); after a
    barrier confirms every host's shard set is fsynced, process 0 ALONE
    merges the per-process file lists into ``manifest.json`` — which
    also records the saving mesh's axis sizes, ``SpecLayout`` and
    process count — and commits with the same single-rename protocol.
    A process killed at ANY point leaves either a complete committed
    step or an ignorable partial.  ``restore_latest`` reassembles the
    global arrays from every process's shards regardless of the
    restoring cluster's shape — restore-with-reshard is just this
    assembly plus ``apply_state``'s re-``device_put`` onto whatever
    mesh is live (elastic restart: save on N hosts, resume on N-1).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 max_pending: int = 2, sharded: Optional[bool] = None,
                 reap_age_s: float = 3600.0, process_context=None):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.max_pending = max_pending
        self.sharded = sharded
        self.reap_age_s = reap_age_s
        self._process_context = process_context
        os.makedirs(self.directory, exist_ok=True)
        # counters (tests and stats() read these)
        self.saves = 0
        self.corrupt_skipped = 0
        self.shard_files_written = 0
        self.reshard_restores = 0
        # async machinery, started lazily
        self._queue: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._async_error: Optional[BaseException] = None
        # preemption
        self._preempted = False
        self._prev_handlers: Dict[int, Any] = {}
        self._reap_stale_tmp()

    def _ctx(self):
        """The live process context (index/count/barrier) — resolved per
        call so ``emulated_process_context`` tests can flip identities
        between save calls on one checkpointer."""
        if self._process_context is not None:
            return self._process_context
        try:
            from ..distributed import bootstrap

            return bootstrap.cluster_context()
        except Exception:
            class _Solo:
                index, count, is_coordinator = 0, 1, True

                def barrier(self, name, timeout_s=0):
                    pass

            return _Solo()

    # ------------------------------------------------------------ paths
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def steps(self) -> List[int]:
        """Committed checkpoint steps, ascending (no integrity check)."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    continue
        return sorted(out)

    def _reap_stale_tmp(self):
        """Reclaim dead staging dirs without racing live peers.

        Concurrent processes share the checkpoint directory, so "reap
        every ``.tmp-*``" would let process 0 delete process 1's
        in-flight staging mid-write.  Tmp dirs are therefore named with
        the owner's process index + pid, and a process reaps only (a)
        its OWN index-prefix (a previous incarnation of this rank died;
        its replacement holds the slot) or (b) anything older than
        ``reap_age_s`` (orphaned by a rank that never came back).
        Shared sharded staging (``.staging-*``) is cleaned by the
        coordinator alone — at the start of the next save for the same
        step, or here once age-expired."""
        ctx = self._ctx()
        now = self._fs_now()
        own_prefix = f".tmp-p{ctx.index}-"
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if name.startswith(".tmp-"):
                legacy = not name.startswith(".tmp-p")  # pre-sharded
                # naming (no owner encoded): cannot belong to a live
                # peer of this version, safe to reclaim eagerly
                if legacy or name.startswith(own_prefix) or \
                        self._age_expired(path, now):
                    shutil.rmtree(path, ignore_errors=True)
            elif name.startswith(".staging-"):
                if ctx.index == 0 and self._age_expired(path, now):
                    shutil.rmtree(path, ignore_errors=True)

    def _fs_now(self) -> float:
        """Filesystem "now": the mtime of a freshly-touched probe in
        the checkpoint dir.  Ages are differences between FILESYSTEM
        timestamps, so on shared storage (NFS) whose server clock
        drifts from this host's the comparison stays coherent where
        the local wall clock would mis-age a peer's staging."""
        probe = os.path.join(self.directory, ".reap-probe")
        try:
            with open(probe, "w"):
                pass
            return os.path.getmtime(probe)
        except OSError:
            return float("-inf")   # can't tell the time: reap nothing

    def _age_expired(self, path: str, now: float) -> bool:
        try:
            return now - os.path.getmtime(path) > self.reap_age_s
        except OSError:
            return False

    # ------------------------------------------------------------- save
    def save(self, step: int, state: Dict[str, Any]) -> str:
        """Synchronous atomic save; returns the committed directory.

        Stage everything under a process-owned tmp dir, fsync the
        payloads, write the manifest LAST, then commit with one rename —
        at no point does a partially-written checkpoint exist under a
        ``step_*`` name.  When the process context spans more than one
        process (or ``sharded=True``), the sharded elastic protocol is
        used instead (see the class docstring)."""
        if not isinstance(state, dict) or not state:
            raise ValueError("state must be a non-empty dict of "
                             "{name: subtree}")
        ctx = self._ctx()
        use_sharded = (self.sharded if self.sharded is not None
                       else ctx.count > 1)
        if use_sharded:
            return self._save_sharded(step, state, ctx)
        t0 = time.perf_counter()
        self._reap_stale_tmp()
        tmp = os.path.join(
            self.directory,
            f".tmp-p{ctx.index}-{os.getpid()}-{step}-{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp)
        try:
            files = {}
            for key, sub in state.items():
                fname = f"{key}.pkl"
                fpath = os.path.join(tmp, fname)
                with open(fpath, "wb") as f:
                    pickle.dump(host_snapshot(sub), f, protocol=4)
                    f.flush()
                    os.fsync(f.fileno())
                files[fname] = _sha256(fpath)
                chaos.on_save(f"resilience::write:{key}")
            manifest = {"format": _FORMAT, "step": step, "files": files}
            mpath = os.path.join(tmp, _MANIFEST)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            chaos.on_save("resilience::commit")
            final = self._step_dir(step)
            if os.path.exists(final):      # re-save of the same step
                shutil.rmtree(final)
            os.rename(tmp, final)          # THE commit point (atomic)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self.saves += 1
        if _obsreg.enabled():
            reg = _obsreg.get_registry()
            reg.counter("checkpoint_saves_total",
                        "checkpoints committed (atomic renames)").inc()
            reg.histogram("checkpoint_save_seconds",
                          "stage+fsync+commit wall time per checkpoint"
                          ).observe(time.perf_counter() - t0)
        chaos.after_save(final)
        self._gc()
        return final

    # ---------------------------------------------------- sharded save
    def _staging_dir(self, step: int) -> str:
        return os.path.join(self.directory, f".staging-step_{step:08d}")

    def _save_sharded(self, step: int, state: Dict[str, Any], ctx) -> str:
        """The elastic protocol: shards from every process, manifest and
        commit from process 0 alone, barriers at the two hand-offs.

        Every published file (shard pickles, per-process file lists, the
        manifest) goes through tmp+fsync+rename, so a death at any
        instant leaves either nothing or a complete file; the step
        itself becomes visible only at the coordinator's final rename.
        A partially-staged ``.staging-*`` dir is invisible to restore
        and overwritten file-by-file on the next attempt for the step.
        """
        t0 = time.perf_counter()
        self._reap_stale_tmp()
        staging = self._staging_dir(step)
        os.makedirs(staging, exist_ok=True)
        snap = host_snapshot(state)
        flat = _flatten_state(snap)
        arrays = {p: v for p, v in flat.items() if _is_shardable_array(v)}
        meta = {p: v for p, v in flat.items() if p not in arrays}

        files: Dict[str, str] = {}
        leaves: Dict[str, Dict[str, Any]] = {}
        for path in sorted(arrays):
            leaf = arrays[path]
            entry = leaves.setdefault(path, {
                "global_shape": list(leaf.global_shape
                                     if isinstance(leaf, ShardedHostLeaf)
                                     else leaf.shape),
                "dtype": str(leaf.dtype),
                "shards": [],
            })
            for i, (bounds, data) in enumerate(_owned_shards(path, leaf,
                                                             ctx)):
                fname = _shard_fname(path, bounds)
                files[fname] = _write_fsync(
                    os.path.join(staging, fname),
                    pickle.dumps(np.asarray(data), protocol=4),
                    site=f"resilience::shard:{path}:{i}")
                entry["shards"].append({"file": fname,
                                        "index": [list(b) for b in bounds],
                                        "process": ctx.index})
                self.shard_files_written += 1
        if ctx.index == 0 and meta:
            files[_META_FILE] = _write_fsync(
                os.path.join(staging, _META_FILE),
                pickle.dumps(meta, protocol=4),
                site="resilience::write:_meta")
        proc_list = f"process_{ctx.index:04d}.files.json"
        _write_fsync(
            os.path.join(staging, proc_list),
            json.dumps({"files": files, "leaves": leaves},
                       indent=1).encode())
        chaos.on_save("resilience::shards_done")
        ctx.barrier(f"ckpt-{step}-{self.saves}-shards")

        final = self._step_dir(step)
        if ctx.index == 0:
            self._commit_sharded(step, staging, final, ctx)
        ctx.barrier(f"ckpt-{step}-{self.saves}-committed")
        self.saves += 1
        if _obsreg.enabled():
            reg = _obsreg.get_registry()
            reg.counter("checkpoint_saves_total",
                        "checkpoints committed (atomic renames)").inc()
            reg.counter("checkpoint_shard_files_total",
                        "sharded checkpoint files written by this process"
                        ).inc(len(files))
            reg.histogram("checkpoint_save_seconds",
                          "stage+fsync+commit wall time per checkpoint"
                          ).observe(time.perf_counter() - t0)
        if ctx.index == 0:
            chaos.after_save(final)
            self._gc()
        return final

    def _commit_sharded(self, step: int, staging: str, final: str, ctx):
        """Process 0 only: merge every host's file list (all confirmed
        fsynced by the barrier) into one manifest, then rename."""
        merged_files: Dict[str, str] = {}
        merged_leaves: Dict[str, Dict[str, Any]] = {}
        for idx in range(ctx.count):
            ppath = os.path.join(staging, f"process_{idx:04d}.files.json")
            try:
                with open(ppath) as f:
                    plist = json.load(f)
            except (OSError, ValueError) as e:
                raise RuntimeError(
                    f"sharded save {step}: missing/unreadable shard list "
                    f"for process {idx} after barrier ({e})")
            merged_files.update(plist["files"])
            for path, entry in plist["leaves"].items():
                tgt = merged_leaves.setdefault(
                    path, {"global_shape": entry["global_shape"],
                           "dtype": entry["dtype"], "shards": []})
                if tgt["global_shape"] != entry["global_shape"]:
                    raise RuntimeError(
                        f"sharded save {step}: processes disagree on "
                        f"{path} global shape ({tgt['global_shape']} vs "
                        f"{entry['global_shape']})")
                tgt["shards"].extend(entry["shards"])
        manifest = {
            "format": _FORMAT_SHARDED,
            "step": step,
            "sharded": True,
            "mesh": _mesh_metadata(ctx.count),
            "files": merged_files,
            "leaves": merged_leaves,
            "meta_file": _META_FILE if _META_FILE in merged_files else None,
        }
        chaos.on_save("resilience::manifest")
        _write_fsync(os.path.join(staging, _MANIFEST),
                     json.dumps(manifest, indent=1).encode(),
                     site="resilience::commit")
        if os.path.exists(final):      # re-save of the same step
            shutil.rmtree(final)
        os.rename(staging, final)      # THE commit point (atomic)

    def save_async(self, step: int, state: Dict[str, Any]):
        """Snapshot ``state`` to host now, write it from the worker
        thread.  Blocks when ``max_pending`` saves are already queued —
        backpressure instead of unbounded host-memory growth.  An error
        from a previous async save re-raises here (and in ``wait``)."""
        self._raise_async_error()
        snap = host_snapshot(state)
        if self._worker is None:
            self._queue = queue.Queue(maxsize=self.max_pending)
            self._worker = threading.Thread(
                target=self._drain, name="resilient-ckpt", daemon=True)
            self._worker.start()
        self._queue.put((step, snap))      # blocks when full

    def _drain(self):
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                step, snap = item
                self.save(step, snap)
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                self._async_error = e
            finally:
                self._queue.task_done()

    def _raise_async_error(self):
        if self._async_error is not None:
            err, self._async_error = self._async_error, None
            raise err

    def wait(self):
        """Block until every queued async save is committed; re-raise
        the first async failure, if any."""
        if self._queue is not None:
            self._queue.join()
        self._raise_async_error()

    def close(self):
        if self._worker is not None:
            self._queue.join()
            self._queue.put(None)
            self._worker.join()
            self._worker = None
            self._queue = None
        self.uninstall_preemption_handler()
        self._raise_async_error()

    def _gc(self):
        keep = self.steps()
        if self.max_to_keep and len(keep) > self.max_to_keep:
            for step in keep[:-self.max_to_keep]:
                shutil.rmtree(self._step_dir(step), ignore_errors=True)

    # ---------------------------------------------------------- restore
    def _load_verified(self, step: int) -> Dict[str, Any]:
        d = self._step_dir(step)
        mpath = os.path.join(d, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruption(f"{d}: unreadable manifest ({e})")
        fmt = manifest.get("format")
        if fmt == _FORMAT_SHARDED:
            return self._load_sharded(d, manifest)
        if fmt != _FORMAT:
            raise CheckpointCorruption(
                f"{d}: unknown manifest format {manifest.get('format')!r}")
        state = {}
        for fname, digest in manifest.get("files", {}).items():
            fpath = os.path.join(d, fname)
            self._verify_file(d, fname, digest)
            try:
                with open(fpath, "rb") as f:
                    state[fname[:-4]] = pickle.load(f)
            except Exception as e:  # noqa: BLE001 — any unpickle failure
                raise CheckpointCorruption(f"{d}: unreadable {fname} ({e})")
        return state

    def _verify_file(self, d: str, fname: str, digest: str):
        fpath = os.path.join(d, fname)
        if not os.path.exists(fpath):
            raise CheckpointCorruption(f"{d}: missing file {fname}")
        actual = _sha256(fpath)
        if actual != digest:
            raise CheckpointCorruption(
                f"{d}: sha256 mismatch for {fname} "
                f"(manifest {digest[:12]}…, file {actual[:12]}…)")

    def _load_sharded(self, d: str, manifest: Dict[str, Any]
                      ) -> Dict[str, Any]:
        """Verify every shard file, then reassemble the GLOBAL arrays —
        independent of how many processes are restoring (the
        restore-with-reshard half: ``apply_state`` + the live executor
        re-``device_put`` the result onto whatever mesh exists now)."""
        for fname, digest in manifest.get("files", {}).items():
            self._verify_file(d, fname, digest)
        flat: Dict[str, Any] = {}
        meta_file = manifest.get("meta_file")
        if meta_file:
            try:
                with open(os.path.join(d, meta_file), "rb") as f:
                    flat.update(pickle.load(f))
            except Exception as e:  # noqa: BLE001
                raise CheckpointCorruption(
                    f"{d}: unreadable {meta_file} ({e})")
        for path, entry in manifest.get("leaves", {}).items():
            shape = tuple(entry["global_shape"])
            parts = []
            for sh in entry["shards"]:
                try:
                    with open(os.path.join(d, sh["file"]), "rb") as f:
                        parts.append((sh["index"], pickle.load(f)))
                except Exception as e:  # noqa: BLE001
                    raise CheckpointCorruption(
                        f"{d}: unreadable shard {sh['file']} ({e})")
            if not parts:
                raise CheckpointCorruption(f"{d}: no shards for {path}")
            arr = np.empty(shape, dtype=parts[0][1].dtype)
            covered = 0
            for bounds, data in parts:
                sl = tuple(slice(a, b) for a, b in bounds)
                arr[sl] = data
                covered += int(np.prod([b - a for a, b in bounds],
                                       dtype=np.int64)) if bounds else 1
            want = int(np.prod(shape, dtype=np.int64)) if shape else 1
            if covered != want:
                raise CheckpointCorruption(
                    f"{d}: shards for {path} cover {covered} of {want} "
                    f"elements (incomplete shard set committed?)")
            flat[path] = arr
        saved_procs = manifest.get("mesh", {}).get("process_count")
        ctx = self._ctx()
        if saved_procs is not None and saved_procs != ctx.count:
            self.reshard_restores += 1
            if _obsreg.enabled():
                _obsreg.get_registry().counter(
                    "checkpoint_reshard_restores_total",
                    "restores onto a different process topology than "
                    "the save").inc()
        return _unflatten_state(flat)

    def restore(self, step: int) -> Dict[str, Any]:
        """Load and VERIFY one checkpoint; raises
        :class:`CheckpointCorruption` instead of returning bad state."""
        return self._load_verified(step)

    def restore_latest(self) -> Tuple[Optional[int], Optional[Dict]]:
        """Newest checkpoint that passes verification, or ``(None,
        None)``.  Corrupt/torn checkpoints are skipped (and counted in
        ``corrupt_skipped``) — never silently restored."""
        for step in reversed(self.steps()):
            try:
                return step, self._load_verified(step)
            except CheckpointCorruption as e:
                self.corrupt_skipped += 1
                if _obsreg.enabled():
                    _obsreg.get_registry().counter(
                        "checkpoint_corrupt_skipped_total",
                        "corrupt checkpoints skipped during restore"
                    ).inc()
                print(f"[paddle_tpu.resilience] skipping corrupt "
                      f"checkpoint: {e}", file=sys.stderr)
        return None, None

    # ------------------------------------------------------- preemption
    def install_preemption_handler(self, signals=(signal.SIGTERM,)):
        """Latch preemption signals into a flag the training loop polls
        (``preemption_requested``) at batch boundaries.  Main thread
        only (CPython restricts ``signal.signal``)."""
        for s in signals:
            self._prev_handlers[s] = signal.signal(s, self._on_signal)

    def uninstall_preemption_handler(self):
        for s, prev in self._prev_handlers.items():
            signal.signal(s, prev)
        self._prev_handlers.clear()

    def _on_signal(self, signum, frame):
        self._preempted = True

    @property
    def preemption_requested(self) -> bool:
        return self._preempted

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        return {
            "directory": self.directory,
            "steps": self.steps(),
            "saves": self.saves,
            "corrupt_skipped": self.corrupt_skipped,
            "shard_files_written": self.shard_files_written,
            "reshard_restores": self.reshard_restores,
            "pending_async": self._queue.qsize() if self._queue else 0,
            "preemption_requested": self._preempted,
        }
