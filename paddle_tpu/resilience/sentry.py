"""Training-loop guard against numeric poisoning.

One NaN batch — a corrupt example, an overflowed loss scale, a flaky
device — poisons every weight through the fused ``backward + step``
program, and every step after that is wasted.  The reference framework's
answer is ``FLAGS_check_nan_inf`` (detect and abort); a production run
that must survive preemption cannot afford abort-on-first-NaN.

:class:`Sentry` classifies each observed step:

- ``OK``     — finite loss/grad-norm; the consecutive-bad counter resets.
- ``SKIP``   — non-finite: the batch should be dropped and the update
  rolled back (the ``ResilienceCallback`` restores its in-memory
  snapshot of the pre-step state), after an exponential backoff pause
  (transient infra faults — a flaky remote device, a mid-migration VM —
  heal with time; immediate retry just burns the next batch too).
- ``REWIND`` — K consecutive bad steps: the poison is persistent
  (corrupted weights, a bad data shard), so roll state back to the last
  good on-disk checkpoint instead of skipping forever.

The sentry only CLASSIFIES; state movement belongs to the callback (or
any custom loop driving :meth:`observe` directly).
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

__all__ = ["Sentry", "OK", "SKIP", "REWIND", "is_finite"]

OK = "ok"
SKIP = "skip"
REWIND = "rewind"


def is_finite(value) -> bool:
    """Finiteness of a loss/grad-norm in whatever form the loop has it:
    Tensor, jax/numpy array, python float, or None (vacuously finite)."""
    if value is None:
        return True
    if hasattr(value, "numpy"):
        value = value.numpy()
    try:
        return bool(np.isfinite(np.asarray(value)).all())
    except TypeError:
        return True


class Sentry:
    def __init__(self, max_consecutive_bad: int = 3,
                 backoff_base_s: float = 0.0,
                 backoff_factor: float = 2.0,
                 backoff_max_s: float = 30.0):
        if max_consecutive_bad < 1:
            raise ValueError("max_consecutive_bad must be >= 1")
        self.max_consecutive_bad = max_consecutive_bad
        self.backoff_base_s = backoff_base_s
        self.backoff_factor = backoff_factor
        self.backoff_max_s = backoff_max_s
        # counters
        self.steps_seen = 0
        self.bad_steps = 0
        self.skips = 0
        self.rewinds = 0
        self.consecutive_bad = 0
        self.last_backoff_s = 0.0

    def observe(self, loss=None, grad_norm=None) -> str:
        """Classify one training step; returns ``OK``/``SKIP``/``REWIND``."""
        self.steps_seen += 1
        if is_finite(loss) and is_finite(grad_norm):
            self.consecutive_bad = 0
            return OK
        self.bad_steps += 1
        self.consecutive_bad += 1
        self._backoff()
        if self.consecutive_bad >= self.max_consecutive_bad:
            self.rewinds += 1
            self.consecutive_bad = 0
            return REWIND
        self.skips += 1
        return SKIP

    def _backoff(self):
        if self.backoff_base_s <= 0:
            self.last_backoff_s = 0.0
            return
        delay = min(
            self.backoff_base_s
            * self.backoff_factor ** (self.consecutive_bad - 1),
            self.backoff_max_s)
        self.last_backoff_s = delay
        time.sleep(delay)

    def stats(self) -> dict:
        return {
            "steps_seen": self.steps_seen,
            "bad_steps": self.bad_steps,
            "skips": self.skips,
            "rewinds": self.rewinds,
            "consecutive_bad": self.consecutive_bad,
            "last_backoff_s": self.last_backoff_s,
        }
