"""``ResilienceCallback`` — checkpoint-recoverable, NaN-guarded,
preemption-aware ``hapi.Model.fit``.

One callback wires the whole resilience story into the high-level loop:

- **Resume**: on train begin, restore the newest VALID checkpoint
  (``ResilientCheckpointer.restore_latest`` skips corrupt ones), then
  fast-forward the data stream past the ``resume_step`` batches that
  are already baked into the restored weights — the loop replays the
  epoch structure without re-executing trained batches, so a killed run
  that resumes reaches final weights bit-identical to an uninterrupted
  one (tests/test_resilience.py proves this under injected kills).
- **Checkpointing**: every ``save_every`` batches, atomically and (with
  ``async_save=True``) off-thread behind a bounded queue.
- **Guard**: after each batch, feed the loss to a :class:`Sentry`; on
  ``SKIP`` roll model+optimizer back to the in-memory snapshot of the
  pre-batch state (the poisoned update is undone, the batch is
  skipped); on ``REWIND`` restore the last good on-disk checkpoint.
- **Preemption**: SIGTERM latches a flag; at the next batch boundary
  the callback saves synchronously and stops training cleanly
  (``model.stop_training``), the fleet-elastic contract.

Chaos hooks (``resilience.chaos``) fire inside this callback's step
path, so every fault above is injectable deterministically from tests.
"""
from __future__ import annotations

import sys
from typing import Optional

from ..hapi.callbacks import Callback
from . import chaos
from .checkpoint import ResilientCheckpointer, apply_state, collect_state
from .sentry import OK, REWIND, SKIP, Sentry

__all__ = ["ResilienceCallback"]


class ResilienceCallback(Callback):
    def __init__(self, checkpoint_dir: str, save_every: int = 1,
                 max_to_keep: int = 3, async_save: bool = False,
                 resume: bool = True, guard: bool = True,
                 sentry: Optional[Sentry] = None,
                 handle_preemption: bool = True, verbose: int = 0):
        super().__init__()
        if save_every < 1:
            raise ValueError("save_every must be >= 1")
        self.checkpoint_dir = checkpoint_dir
        self.save_every = save_every
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.resume = resume
        self.guard = guard
        self.sentry = sentry or Sentry()
        self.handle_preemption = handle_preemption
        self.verbose = verbose
        self.checkpointer: Optional[ResilientCheckpointer] = None
        self.global_step = 0          # batches completed (trained/skipped)
        self.resume_step = 0
        self.events = []              # [(kind, step)] — observability
        self._last_good = None

    # ------------------------------------------------------------ state
    def _network(self):
        return self.model.network

    def _optimizer(self):
        return getattr(self.model, "_optimizer", None)

    def _state(self):
        return collect_state(self._network(), self._optimizer(),
                             extra={"meta": {"global_step":
                                             self.global_step}})

    def _apply(self, state):
        apply_state(state, self._network(), self._optimizer())

    def _log(self, msg):
        if self.verbose:
            print(f"[resilience] {msg}", file=sys.stderr)

    # -------------------------------------------------------- lifecycle
    def on_train_begin(self, logs=None):
        self.checkpointer = ResilientCheckpointer(
            self.checkpoint_dir, max_to_keep=self.max_to_keep)
        if self.handle_preemption:
            self.checkpointer.install_preemption_handler()
        self.global_step = 0
        self.resume_step = 0
        if self.resume:
            step, state = self.checkpointer.restore_latest()
            if step is not None:
                self._apply(state)
                self.resume_step = step
                self.events.append(("resume", step))
                self._log(f"resumed from step {step} "
                          f"({self.checkpointer.corrupt_skipped} corrupt "
                          "checkpoint(s) skipped)")
        if self.guard:
            self._last_good = self._state()

    def on_train_batch_begin(self, step, logs=None):
        if self.global_step < self.resume_step:
            # this batch is already baked into the restored weights;
            # consume it from the stream without executing it
            self.model._skip_batch = True
            self.global_step += 1
            return
        try:
            chaos.on_step(self.global_step)
        except chaos.SimulatedPreemption:
            # the run is dying mid-fit, so on_train_end never fires:
            # flush queued async saves and release the signal handler
            # here instead of leaking them past the abort
            self.checkpointer.close()
            raise

    def on_train_batch_end(self, step, logs=None):
        self.global_step += 1
        verdict = self.sentry.observe((logs or {}).get("loss")) \
            if self.guard else OK
        if verdict == OK:
            if self.guard:
                self._last_good = self._state()
            if self.global_step % self.save_every == 0:
                self._save()
        elif verdict == SKIP:
            self.events.append(("skip", self.global_step - 1))
            self._log(f"non-finite loss at step {self.global_step - 1}: "
                      "rolled back, batch skipped")
            self._apply(self._last_good)
        else:  # REWIND
            ckpt_step, state = self.checkpointer.restore_latest()
            self.events.append(("rewind", ckpt_step))
            if state is not None:
                self._apply(state)
                self._last_good = self._state()
                self._log(f"{self.sentry.max_consecutive_bad} consecutive "
                          f"bad steps: rewound to checkpoint {ckpt_step}")
            else:
                self._apply(self._last_good)
                self._log("rewind requested but no valid checkpoint; "
                          "rolled back to last good in-memory state")
        if self.handle_preemption and \
                self.checkpointer.preemption_requested:
            self.checkpointer.wait()
            self.checkpointer.save(self.global_step, self._state())
            self.events.append(("preempt-save", self.global_step))
            self._log(f"preemption signal: saved step {self.global_step}, "
                      "stopping")
            self.model.stop_training = True

    def _save(self):
        state = self._state()
        if self.async_save:
            self.checkpointer.save_async(self.global_step, state)
        else:
            self.checkpointer.save(self.global_step, state)
        self.events.append(("save", self.global_step))

    def on_train_end(self, logs=None):
        if self.checkpointer is not None:
            self.checkpointer.close()
