"""paddle_tpu.resilience — fault-injected, checkpoint-recoverable
training and serving.

The ROADMAP north star (production traffic from millions of users) is
unreachable without surviving preemption, disk corruption, and poisoned
inputs — and without a harness that PROVES we survive them.  This
package is both halves:

- :mod:`chaos`      — deterministic, seeded fault injection
  (:class:`FaultPlan`): NaN/Inf batches, crash-mid-checkpoint,
  truncated/bit-flipped checkpoint files, delayed/killed/SIGTERMed
  training steps, poisoned serving requests.
- :mod:`checkpoint` — :class:`ResilientCheckpointer`: atomic
  rename-commit saves, per-file sha256 manifests, ``restore_latest``
  that falls back to the newest VALID checkpoint, bounded async save
  queue with backpressure, SIGTERM save-and-exit.
- :mod:`sentry`     — :class:`Sentry`: NaN/Inf loss and grad-norm
  detection, skip-with-exponential-backoff, rewind after K consecutive
  bad steps.
- :mod:`callback`   — :class:`ResilienceCallback` wiring all of the
  above into ``hapi.Model.fit`` (resume + fast-forward, periodic atomic
  saves, rollback on poison, graceful preemption stop).

Serving hardening (per-request deadlines, poison-request isolation)
lives in :mod:`paddle_tpu.serving` and consults :mod:`chaos` hooks.

Recovery guarantees (README "Resilience" documents the fault model):
under injected kill/corruption faults, a resumed run reaches final
weights bit-identical to an uninterrupted one, and a corrupt checkpoint
is never restored — both asserted by ``tests/test_resilience.py``.
"""
from __future__ import annotations

from . import chaos
from .callback import ResilienceCallback
from .chaos import ChaosError, FaultPlan, SimulatedPreemption
from .checkpoint import (CheckpointCorruption, ResilientCheckpointer,
                         ShardedHostLeaf, apply_state, collect_state,
                         host_snapshot)
from .sentry import OK, REWIND, SKIP, Sentry

__all__ = [
    "FaultPlan",
    "ChaosError",
    "SimulatedPreemption",
    "chaos",
    "ResilientCheckpointer",
    "ShardedHostLeaf",
    "CheckpointCorruption",
    "collect_state",
    "apply_state",
    "host_snapshot",
    "Sentry",
    "OK",
    "SKIP",
    "REWIND",
    "ResilienceCallback",
]
