# lint-tpu: disable-file=L004 -- serving-layer host-side control plane
# (like router.py); new backend code belongs under core/ ops/ kernels/
# static/ distributed/ (README: Repo lint)
"""Multi-tenant trace replay for the serving fleet router
(``BENCH_ONLY=router_replay``; README "Serving fleet & router").

A *trace* is a seeded, deterministic arrival schedule over a few tenant
archetypes — the mixes a real fleet sees at once:

* **chat** — many short requests sharing one long system prompt (the
  prefix-affinity jackpot: after the first request lands, every
  follow-up re-prefills only its tail);
* **long** — few requests with long, mostly-unique prompts (prefill
  pressure; affinity helps only within the tenant's shared preamble);
* **burst** — a clump of near-simultaneous short arrivals (queueing
  pressure; load-term territory).

``build_trace`` materializes the schedule (all randomness from ONE
``numpy.random.RandomState(seed)`` — same seed, same trace, byte for
byte); ``replay_trace`` feeds it through a :class:`Router` step by
step and reports per-tenant goodput and TTFT tails plus fleet-level
placement/cache counters.  The bench (bench.py ``router_replay``) runs
ONE trace through an affinity fleet and a round-robin fleet and prints
both — the affinity fleet should win on cached-token ratio and not
lose on p99 TTFT at equal load.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from .router import Router
from .scheduler import AdmissionError


@dataclass
class Tenant:
    """One workload archetype in the replayed mix."""

    name: str
    kind: str = "chat"                # "chat" | "long" | "burst"
    requests: int = 8
    shared_prefix_tokens: int = 48    # tokens every request shares
    tail_tokens: tuple = (4, 16)      # unique suffix length range
    max_new_tokens: int = 8
    deadline_s: Optional[float] = None
    priority: int = 0
    # sampled-tenant archetype (ISSUE 19): temperature > 0 routes the
    # tenant's requests through the seeded sampling path; each request
    # gets a trace-deterministic per-request seed so the same trace
    # replays the same token streams byte for byte
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0


def default_tenants() -> List[Tenant]:
    """The stock four-tenant mix (module docstring): a chatty tenant
    with a big shared system prompt, a long-prompt tenant, a burst
    tenant that clumps its arrivals, and a sampled tenant exercising
    the seeded temperature/top-k/top-p decode path."""
    return [
        Tenant("chat", kind="chat", requests=10,
               shared_prefix_tokens=48, tail_tokens=(4, 12),
               max_new_tokens=8),
        Tenant("long", kind="long", requests=4,
               shared_prefix_tokens=16, tail_tokens=(40, 72),
               max_new_tokens=6),
        Tenant("burst", kind="burst", requests=8,
               shared_prefix_tokens=24, tail_tokens=(2, 8),
               max_new_tokens=4),
        Tenant("sampled", kind="chat", requests=4,
               shared_prefix_tokens=32, tail_tokens=(4, 10),
               max_new_tokens=6, temperature=0.8, top_k=16, top_p=0.95),
    ]


@dataclass
class Arrival:
    """One request of the trace: submit at router-iteration ``step``."""

    step: int
    tenant: str
    prompt: np.ndarray
    max_new_tokens: int
    deadline_s: Optional[float]
    priority: int
    request_id: str = ""
    # seeded sampling (0.0 temperature = greedy, seed ignored)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None


def build_trace(tenants: Optional[Sequence[Tenant]] = None, *,
                seed: int = 0, horizon: int = 24, vocab: int = 256
                ) -> List[Arrival]:
    """Materialize the deterministic arrival schedule.

    Every tenant gets a seeded shared prefix; each of its requests is
    that prefix plus a seeded unique tail.  chat/long arrivals spread
    uniformly over ``horizon`` router iterations; a burst tenant clumps
    ALL its arrivals into a two-iteration window.  Token id 0 is
    avoided (tiny test models use 0 as pad/eos)."""
    tenants = list(tenants) if tenants is not None else default_tenants()
    rng = np.random.RandomState(seed)

    def toks(n):
        return rng.randint(1, vocab, size=n).astype(np.int32)

    arrivals: List[Arrival] = []
    for t in tenants:
        shared = toks(t.shared_prefix_tokens)
        if t.kind == "burst":
            start = int(rng.randint(0, max(1, horizon - 2)))
            steps = start + rng.randint(0, 2, size=t.requests)
        else:
            steps = rng.randint(0, horizon, size=t.requests)
        lo, hi = t.tail_tokens
        for i in range(t.requests):
            tail = toks(int(rng.randint(lo, hi + 1)))
            arrivals.append(Arrival(
                step=int(steps[i]), tenant=t.name,
                prompt=np.concatenate([shared, tail]),
                max_new_tokens=t.max_new_tokens,
                deadline_s=t.deadline_s, priority=t.priority,
                request_id=f"{t.name}-{i}",
                temperature=t.temperature, top_k=t.top_k, top_p=t.top_p,
                # per-request seed drawn from the trace rng: sampled
                # outputs are as reproducible as the schedule itself
                seed=(int(rng.randint(0, 2**31 - 1))
                      if t.temperature > 0 else None)))
    # stable order: by arrival step, tenant name, then index — NOT by
    # rng state, so the submit order is reproducible and readable
    arrivals.sort(key=lambda a: (a.step, a.tenant, a.request_id))
    return arrivals


def _pctl(values: List[float], q: float) -> Optional[float]:
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]


@dataclass
class _TenantTally:
    submitted: int = 0
    finished: Dict[str, int] = field(default_factory=dict)
    goodput_tokens: int = 0
    ttfts: List[float] = field(default_factory=list)


def replay_trace(router: Router, trace: Sequence[Arrival]) -> dict:
    """Feed ``trace`` through ``router`` — each arrival submits at its
    scheduled iteration between ``router.step()`` calls, then the fleet
    drains — and report per-tenant outcomes plus fleet counters.

    Goodput follows metrics.py: tokens from requests finishing inside
    their SLO (eos/stop/length).  TTFTs come from the finishing
    replica's request timelines (compile excluded as long as the caller
    warmed the fleet first — bench.py does)."""
    pending = sorted(trace, key=lambda a: a.step)
    tallies: Dict[str, _TenantTally] = {}
    by_rid: Dict[str, str] = {}
    i = 0
    step = 0
    results: Dict[str, object] = {}
    while i < len(pending) or router.has_work():
        while i < len(pending) and pending[i].step <= step:
            a = pending[i]
            i += 1
            tally = tallies.setdefault(a.tenant, _TenantTally())
            tally.submitted += 1
            by_rid[a.request_id] = a.tenant
            try:
                router.submit(a.prompt,
                              max_new_tokens=a.max_new_tokens,
                              deadline_s=a.deadline_s,
                              priority=a.priority,
                              request_id=a.request_id,
                              temperature=a.temperature,
                              do_sample=a.temperature > 0,
                              top_k=a.top_k, top_p=a.top_p,
                              seed=a.seed)
            except AdmissionError:
                # bounded-queue backpressure is a legitimate outcome of
                # an overload trace — tally it, don't crash the replay
                tally.finished["rejected"] = \
                    tally.finished.get("rejected", 0) + 1
        router.step()
        step += 1
    results.update(router.run_until_complete())
    # one timeline lookup per finished request, from whichever replica
    # finished it (resubmitted requests have a timeline on each replica
    # they visited; the finishing one has finished_ns set)
    timelines: Dict[str, dict] = {}
    for rep in router.replicas:
        for rid, t in rep.engine.metrics.requests.items():
            if t.finished_ns:
                timelines[rid] = t.to_dict()
    for rid, req in results.items():
        tenant = by_rid.get(rid)
        if tenant is None:
            continue
        tally = tallies[tenant]
        reason = req.finish_reason or "unknown"
        tally.finished[reason] = tally.finished.get(reason, 0) + 1
        if reason in ("eos", "stop", "length"):
            tally.goodput_tokens += req.num_generated
        tl = timelines.get(rid)
        if tl is not None and tl["ttft_s"] is not None:
            tally.ttfts.append(tl["ttft_s"])
    fleet_ttfts = [t for tally in tallies.values() for t in tally.ttfts]
    stats = router.stats()
    return {
        "tenants": {
            name: {
                "submitted": tally.submitted,
                "finished": dict(sorted(tally.finished.items())),
                "goodput_tokens": tally.goodput_tokens,
                "mean_ttft_s": (sum(tally.ttfts) / len(tally.ttfts)
                                if tally.ttfts else None),
                "p99_ttft_s": _pctl(tally.ttfts, 0.99),
            }
            for name, tally in sorted(tallies.items())
        },
        "fleet": {
            "policy": router.policy,
            "requests": len(results),
            "cached_token_ratio": stats["router"]["cached_token_ratio"],
            "placements": stats["router"]["placements"],
            "shed_global": stats["router"]["requests_shed_global"],
            "quarantines": stats["router"]["replica_quarantines"],
            "resubmits": stats["router"]["requests_resubmitted"],
            "p99_ttft_s": _pctl(fleet_ttfts, 0.99),
            "mean_ttft_s": (sum(fleet_ttfts) / len(fleet_ttfts)
                            if fleet_ttfts else None),
        },
    }


__all__ = ["Tenant", "Arrival", "default_tenants", "build_trace",
           "replay_trace"]
