"""Request lifecycle + scheduling policy (PAPERS.md: Orca's
iteration-level scheduling).

Policy, in one paragraph: admission is FCFS by arrival ordinal over a
BOUNDED wait queue (a full queue rejects at submit time — backpressure
instead of unbounded latency).  A request is admitted only when the
block pool can hold its prompt plus one decode block (capacity-based
admission control).  When a running sequence needs a block and the pool
is dry, the YOUNGEST running request is preempted — evict-and-requeue
at the queue head, keeping its original ordinal — so the oldest work
always finishes first and no request starves (the fairness half of
"FCFS + fairness").  Preemption drops the victim's generated tokens and
recomputes from the prompt on re-admission (vLLM's "recompute" mode);
under greedy decoding the final output is unchanged.

Termination is the SAME check ``generate()`` uses:
``models.generation.match_stop`` over the generated suffix, plus
eos_token_id and max_new_tokens.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional

import numpy as np

from ..models.generation import match_stop, normalize_stop_sequences


class AdmissionError(Exception):
    """Request rejected at submit time (backpressure or impossible fit)."""


class QueueFull(AdmissionError):
    """The bounded wait queue is at capacity.  Distinguished from the
    impossible-fit AdmissionError so the engine's overload layer can
    respond differently: a higher-priority arrival may shed the
    lowest-priority waiting request instead of being turned away."""


# request states
QUEUED = "queued"
PREFILLING = "prefilling"   # admitted; prompt chunks still being computed
RUNNING = "running"
PREEMPTED = "preempted"
FINISHED = "finished"

_ordinal = itertools.count()


@dataclass(eq=False)
class Request:
    """One generation request and its runtime state.  Identity equality
    (``eq=False``): requests are mutable runtime objects living in
    scheduler lists — field comparison over numpy prompts is both
    ambiguous and wrong."""

    prompt: np.ndarray                      # 1-D int32 token ids
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    stop_sequences: List[List[int]] = field(default_factory=list)
    request_id: str = ""
    # per-request SLO on the MONOTONIC clock (time.monotonic, immune to
    # wall-clock steps — hazard H111): the request is retired with
    # finish_reason "timeout" once deadline_s seconds have elapsed since
    # submission, whether it is still queued or mid-decode (partial
    # tokens kept)
    deadline_s: Optional[float] = None
    # priority class for overload control (serving/overload.py): higher
    # wins.  Admission prefers the highest-priority waiting request,
    # preemption and queue-full shedding take the LOWEST priority first
    # (youngest within a class).  All-default workloads reduce exactly
    # to the FCFS + fairness policy above.
    priority: int = 0
    # sampling spec (serving/sampling.SamplingParams) or None for
    # greedy; sampling_key is the request's base PRNG key ([2] uint32),
    # fixed at submit so preemption + recompute replays the exact token
    # stream (keys are derived from TOKEN INDEX, not step count)
    sampling: Optional[object] = None
    sampling_key: Optional[np.ndarray] = field(default=None, repr=False)
    # streaming (serving/stream.py): on_token fires once per ACCEPTED
    # token; token_deadline_s is a ROLLING inter-token SLO — the
    # monotonic token_deadline_t resets on every emitted token, and a
    # stream that stalls past it times out like a busted deadline_s
    # (it also bounds time-to-first-token, so the load shedder treats
    # it as an effective TTFT deadline)
    on_token: Optional[object] = field(default=None, repr=False)
    token_deadline_s: Optional[float] = None
    token_deadline_t: Optional[float] = field(default=None, repr=False)
    # runtime (engine-owned)
    ordinal: int = field(default_factory=lambda: next(_ordinal))
    state: str = QUEUED
    slot: Optional[int] = None
    blocks: List[int] = field(default_factory=list)
    generated: List[int] = field(default_factory=list)
    # "eos" | "stop" | "length" | "timeout" | "error"
    finish_reason: Optional[str] = None
    error: Optional[str] = None             # set with finish_reason "error"
    preemptions: int = 0
    deadline_t: Optional[float] = field(default=None, repr=False)
    # chunked-prefill progress (engine-owned): tokens whose KV is
    # already in the pool, how many of those came from the prefix cache,
    # and how many prefill chunks this admission has run
    prefill_pos: int = 0
    cached_tokens: int = 0
    prefill_chunks: int = 0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if not self.request_id:
            self.request_id = f"req-{self.ordinal}"
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.deadline_s is not None:
            if self.deadline_s < 0:
                raise ValueError("deadline_s must be >= 0")
            self.deadline_t = time.monotonic() + self.deadline_s
        if self.token_deadline_s is not None:
            if self.token_deadline_s < 0:
                raise ValueError("token_deadline_s must be >= 0")
            self.token_deadline_t = time.monotonic() + self.token_deadline_s

    def expired(self) -> bool:
        """Past the per-request deadline or the rolling inter-token
        deadline (both on the monotonic clock)."""
        if self.deadline_t is not None \
                and time.monotonic() >= self.deadline_t:
            return True
        return self.token_deadline_t is not None \
            and time.monotonic() >= self.token_deadline_t

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    @property
    def num_generated(self) -> int:
        return len(self.generated)

    @property
    def total_len(self) -> int:
        """Current cache frontier: prompt + tokens already written."""
        return self.prompt_len + self.num_generated

    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens (terminator included)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])


class Scheduler:
    """FCFS + fairness policy over a bounded wait queue (module
    docstring).  The scheduler DECIDES (admit / victim / finished); the
    engine executes (prefill, decode, block moves)."""

    def __init__(self, pool, max_queue_len: int = 64):
        self.pool = pool
        self.max_queue_len = max_queue_len
        self.waiting: Deque[Request] = deque()
        self.running: List[Request] = []

    # -------------------------------------------------------- admission
    def enqueue(self, req: Request):
        """Accept into the wait queue, or raise AdmissionError.  A
        request whose full sequence can never fit the pool is rejected
        outright — queuing it would deadlock the head of the queue."""
        total = self.pool.blocks_for(req.prompt_len + req.max_new_tokens)
        if total > self.pool.capacity_blocks:
            raise AdmissionError(
                f"{req.request_id}: needs {total} blocks at full length, "
                f"pool capacity is {self.pool.capacity_blocks}")
        if len(self.waiting) >= self.max_queue_len:
            raise QueueFull(
                f"wait queue full ({self.max_queue_len}); retry later")
        self.waiting.append(req)

    def shed_candidate(self, priority: int) -> Optional[Request]:
        """Waiting request a ``priority``-class arrival may displace
        when the queue is full: the LOWEST-priority (youngest within
        the class) waiting request, and only when its priority is
        strictly below the arrival's.  None when nobody qualifies —
        same-priority traffic keeps the plain bounded-queue rejection."""
        if not self.waiting:
            return None
        victim = min(self.waiting, key=lambda r: (r.priority, -r.ordinal))
        return victim if victim.priority < priority else None

    def requeue_preempted(self, req: Request):
        """Victim goes to the HEAD of the queue with its original
        ordinal: it is the next admitted, so preemption never reorders
        completion past FCFS."""
        req.state = PREEMPTED
        req.slot = None
        req.blocks = []
        req.generated = []
        req.prefill_pos = 0
        req.cached_tokens = 0
        req.prefill_chunks = 0
        self.waiting.appendleft(req)

    def next_admittable(self) -> Optional[Request]:
        """Head of the queue if the pool can hold its prompt + one
        decode block right now; None otherwise (strict FCFS: a blocked
        head blocks the tail, so completions stay in arrival order).
        Prefix-cache hits shrink the bill: blocks matched in the pool's
        content index need no fresh allocation (``admission_plan``
        accounts for matched blocks parked in the evictable LRU)."""
        if not self.waiting:
            return None
        # highest priority class first, FCFS ordinal within a class —
        # for all-default priorities this is exactly the old head-of-
        # deque pick (preempted requests re-queued at the head always
        # carry the smallest ordinals among waiting)
        head = min(self.waiting, key=lambda r: (-r.priority, r.ordinal))
        # uncached prompt blocks + room for the first generated token's
        # write position (a new block only when the prompt fills its
        # last one)
        _, _, feasible = self.pool.admission_plan(head.prompt,
                                                  extra_tokens=1)
        if not feasible:
            return None
        self.waiting.remove(head)
        return head

    # ------------------------------------------------------- preemption
    def pick_victim(self) -> Optional[Request]:
        """Lowest-priority running request, youngest within the class —
        the least completed work lost, and the last in FCFS order
        anyway.  The requester itself may be the victim (it self-
        preempts rather than evicting older work).  With all-default
        priorities this is exactly the old youngest-first pick."""
        if not self.running:
            return None
        return max(self.running, key=lambda r: (-r.priority, r.ordinal))

    # ------------------------------------------------------ termination
    @staticmethod
    def finish_reason(req: Request) -> Optional[str]:
        """Termination check over the request's generated tokens —
        shared semantics with ``generate()`` (same match_stop) — plus
        the monotonic-clock deadline (a hard SLO: it wins over eos/stop
        and fires even before the first token)."""
        if req.expired():
            return "timeout"
        if not req.generated:
            return None
        if req.eos_token_id is not None \
                and req.generated[-1] == req.eos_token_id:
            return "eos"
        if req.stop_sequences and match_stop(req.generated,
                                             req.stop_sequences):
            return "stop"
        if req.num_generated >= req.max_new_tokens:
            return "length"
        return None


__all__ = ["AdmissionError", "QueueFull", "Request", "Scheduler",
           "QUEUED", "PREFILLING", "RUNNING", "PREEMPTED", "FINISHED",
           "normalize_stop_sequences"]
