"""Request-level observability for the serving engine.

Per-request timings (TTFT, TPOT, queue time, tokens generated) plus
engine-level counters and gauges (batch occupancy, cache utilization,
preemptions), exportable three ways:

- ``as_dict()`` — everything, JSON-ready (the metrics schema in
  README "Serving");
- live host ranges into an ACTIVE ``paddle_tpu.profiler`` session
  (request lifecycle spans land in the same chrome trace as the
  framework's host ranges and the XLA device lanes);
- ``export_chrome(path)`` — standalone chrome://tracing JSON of the
  recorded request spans when no profiler session was running;
- the shared ``paddle_tpu.observability`` registry — every lifecycle
  event is mirrored (``serving_*`` counters/gauges, TTFT/TPOT/queue/e2e
  latency histograms) whenever telemetry is enabled, so serving shows
  up in the same Prometheus/JSON exports as training and resilience.

The ``as_dict()`` schema is a contract (README "Serving") and is
unchanged by the registry mirror.
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..observability import registry as _obsreg


def _now_ns() -> int:
    return time.perf_counter_ns()


@dataclass
class RequestTimeline:
    """Wall-clock milestones of one request (perf_counter_ns)."""

    submitted_ns: int = 0
    admitted_ns: int = 0          # last admission (re-set on re-admit)
    first_token_ns: int = 0
    finished_ns: int = 0
    tokens_generated: int = 0
    preemptions: int = 0
    finish_reason: Optional[str] = None

    def to_dict(self) -> dict:
        ttft = (self.first_token_ns - self.submitted_ns) / 1e9 \
            if self.first_token_ns else None
        queue_time = (self.admitted_ns - self.submitted_ns) / 1e9 \
            if self.admitted_ns else None
        # time-per-output-token over the decode phase (tokens after the
        # first, which prefill produced)
        tpot = None
        if self.finished_ns and self.tokens_generated > 1:
            tpot = ((self.finished_ns - self.first_token_ns) / 1e9
                    / (self.tokens_generated - 1))
        return {
            "ttft_s": ttft,
            "tpot_s": tpot,
            "queue_time_s": queue_time,
            "e2e_s": ((self.finished_ns - self.submitted_ns) / 1e9
                      if self.finished_ns else None),
            "tokens_generated": self.tokens_generated,
            "preemptions": self.preemptions,
            "finish_reason": self.finish_reason,
        }


class ServingMetrics:
    def __init__(self):
        # counters
        self.submitted = 0
        self.rejected = 0
        self.completed = 0          # every retirement, any finish_reason
        self.timed_out = 0          # retired past their deadline_s
        self.failed = 0             # retired with finish_reason "error"
        self.preempted = 0          # preemption EVENTS (re-admits recount)
        self.tokens_generated = 0
        self.decode_iterations = 0
        self.prefills = 0
        # prefix cache / chunked prefill
        self.prefix_cache_hits = 0      # admissions reusing >= 1 block
        self.prefix_cache_misses = 0    # admissions reusing none
        self.prefix_cache_evictions = 0
        self.prefill_chunks = 0
        self._cached_tokens_sum = 0
        self._prompt_tokens_sum = 0
        # overload control (serving/overload.py)
        self.shed = 0               # retired with finish_reason "shed"
        self.goodput_tokens = 0     # tokens from requests that BEAT
        #                             their deadline (or had none)
        self.watchdog_stalls = 0    # step attempts over the budget
        self.step_retries = 0       # watchdog retry attempts
        self.degradation_level = 0  # gauge: current ladder level
        self.health_state = 0       # gauge: 0 serving / 1 degraded / 2 failed
        # speculative decoding (serving/speculative.py)
        self.spec_tokens_drafted = 0    # draft proposals verified
        self.spec_tokens_accepted = 0   # proposals the target accepted
        # streaming (serving/stream.py): requests with an on_token
        # callback currently in flight
        self.stream_active = 0
        # quantized serving (kernels/kv_quant): numeric dtype code of
        # the engine's KV pool (0 fp32 / 1 int8 / 2 fp8) and the f32
        # scale-sidecar bytes one block carries (0 unquantized)
        self.kv_cache_dtype_code = 0
        self.kv_quant_scale_bytes = 0
        # gauge accumulators (sampled once per decode iteration)
        self._occupancy_sum = 0.0
        self._cache_util_sum = 0.0
        self._gauge_samples = 0
        self.last_batch_occupancy = 0.0
        self.last_cache_utilization = 0.0
        # per-request
        self.requests: Dict[str, RequestTimeline] = {}
        # chrome spans: (name, start_ns, end_ns, category)
        self._spans: List[tuple] = []

    # handles are looked up per event (not cached) so a test calling
    # ``registry.clear()`` never leaves a mirror pointing at dead metrics
    @staticmethod
    def _obs():
        return _obsreg.get_registry() if _obsreg.enabled() else None

    # ------------------------------------------------------- lifecycle
    def on_submit(self, request_id: str):
        self.submitted += 1
        self.requests[request_id] = RequestTimeline(submitted_ns=_now_ns())
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_requests_submitted_total",
                        "requests submitted to the engine").inc()

    def on_reject(self):
        self.rejected += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_requests_rejected_total",
                        "requests rejected at admission").inc()

    def on_admit(self, request_id: str):
        t = self.requests[request_id]
        was = t.admitted_ns
        t.admitted_ns = _now_ns()
        self.prefills += 1
        if was == 0:
            self._span(f"queued:{request_id}", t.submitted_ns,
                       t.admitted_ns)
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_prefills_total", "prefill passes").inc()
            if was == 0:
                reg.histogram(
                    "serving_queue_seconds",
                    "submit-to-first-admission wait").observe(
                        (t.admitted_ns - t.submitted_ns) / 1e9)

    def on_first_token(self, request_id: str):
        t = self.requests[request_id]
        if t.first_token_ns == 0:
            t.first_token_ns = _now_ns()
            reg = self._obs()
            if reg is not None:
                reg.histogram("serving_ttft_seconds",
                              "time to first token").observe(
                                  (t.first_token_ns - t.submitted_ns) / 1e9)

    def on_prefix_lookup(self, request_id: str, cached_tokens: int,
                         prompt_tokens: int):
        """One admission's prefix-cache outcome: how many of the
        prompt's tokens came from cached blocks (0 == miss)."""
        if cached_tokens > 0:
            self.prefix_cache_hits += 1
        else:
            self.prefix_cache_misses += 1
        self._cached_tokens_sum += cached_tokens
        self._prompt_tokens_sum += prompt_tokens
        reg = self._obs()
        if reg is not None:
            if cached_tokens > 0:
                reg.counter("serving_prefix_cache_hits_total",
                            "admissions reusing cached prefix blocks"
                            ).inc()
            else:
                reg.counter("serving_prefix_cache_misses_total",
                            "admissions with no cached prefix").inc()
            reg.gauge("serving_prefix_cached_token_ratio",
                      "prompt tokens served from the prefix cache, "
                      "cumulative ratio").set(
                          self._cached_tokens_sum
                          / max(self._prompt_tokens_sum, 1))

    def on_prefill_complete(self, request_id: str, chunks: int):
        """Prompt fully prefilled in ``chunks`` fixed-shape chunks."""
        self.prefill_chunks += chunks
        reg = self._obs()
        if reg is not None:
            reg.histogram("serving_prefill_chunks_per_request",
                          "prefill chunks per admitted prompt",
                          buckets=(1, 2, 4, 8, 16, 32, 64)
                          ).observe(chunks)

    def on_evictions(self, n: int):
        """``n`` cached blocks evicted from the pool's prefix LRU."""
        self.prefix_cache_evictions += n
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_prefix_cache_evictions_total",
                        "prefix-cache blocks evicted (LRU)").inc(n)

    def on_preempt(self, request_id: str):
        self.preempted += 1
        self.requests[request_id].preemptions += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_preemptions_total",
                        "requests preempted out of the batch").inc()

    def on_finish(self, request_id: str, tokens: int, reason: str):
        self.completed += 1
        if reason == "timeout":
            self.timed_out += 1
        elif reason == "error":
            self.failed += 1
        elif reason == "shed":
            self.shed += 1
        self.tokens_generated += tokens
        # goodput: tokens that were WORTH producing — the request
        # finished inside its SLO (timeouts/sheds/errors contribute 0)
        if reason in ("eos", "stop", "length"):
            self.goodput_tokens += tokens
        t = self.requests[request_id]
        t.finished_ns = _now_ns()
        t.tokens_generated = tokens
        t.finish_reason = reason
        self._span(f"decode:{request_id}", t.first_token_ns, t.finished_ns)
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_requests_completed_total",
                        "requests retired, by finish reason").inc(
                            reason=reason)
            if reason == "timeout":
                reg.counter("serving_requests_timed_out_total",
                            "requests retired past their deadline").inc()
            elif reason == "error":
                reg.counter("serving_requests_failed_total",
                            "requests retired with an error").inc()
            elif reason == "shed":
                reg.counter("serving_requests_shed_total",
                            "requests shed at admission (estimated TTFT "
                            "past the deadline)").inc()
            reg.counter("serving_tokens_generated_total",
                        "tokens produced by decode").inc(tokens)
            if reason in ("eos", "stop", "length"):
                reg.counter("serving_goodput_tokens_total",
                            "tokens from requests finished within "
                            "deadline").inc(tokens)
            d = t.to_dict()
            if d["tpot_s"] is not None:
                reg.histogram("serving_tpot_seconds",
                              "time per output token (decode phase)"
                              ).observe(d["tpot_s"])
            if d["e2e_s"] is not None:
                reg.histogram("serving_e2e_seconds",
                              "submit-to-finish request latency"
                              ).observe(d["e2e_s"])

    # --------------------------------------------- speculative decoding
    def on_spec_commit(self, accepted_len: int):
        """One slot's verify outcome: ``accepted_len`` tokens committed
        this iteration (accepted drafts + the bonus/correction token,
        so 1..K+1)."""
        reg = self._obs()
        if reg is not None:
            reg.histogram("serving_accepted_per_step",
                          "tokens committed per request per speculative "
                          "verify step (accepted drafts + bonus)",
                          buckets=(1, 2, 3, 4, 5, 6, 8, 12, 16)
                          ).observe(accepted_len)

    def on_spec_step(self, drafted: int, accepted: int):
        """One speculative iteration over the bucket: ``drafted`` draft
        proposals verified, ``accepted`` of them kept.  The accept-rate
        gauge is cumulative — the bench's headline speculation signal."""
        self.spec_tokens_drafted += drafted
        self.spec_tokens_accepted += accepted
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_spec_tokens_drafted_total",
                        "draft-model proposals verified by the target"
                        ).inc(drafted)
            reg.counter("serving_spec_tokens_accepted_total",
                        "draft proposals accepted by the target"
                        ).inc(accepted)
            reg.gauge("serving_spec_accept_rate",
                      "accepted / drafted speculative tokens, "
                      "cumulative").set(self.spec_accept_rate())

    def spec_accept_rate(self) -> float:
        return self.spec_tokens_accepted \
            / max(self.spec_tokens_drafted, 1)

    # -------------------------------------------------------- streaming
    def on_stream_start(self):
        self.stream_active += 1
        reg = self._obs()
        if reg is not None:
            reg.gauge("serving_stream_active",
                      "streaming requests currently in flight").set(
                          self.stream_active)

    def on_stream_end(self):
        self.stream_active -= 1
        reg = self._obs()
        if reg is not None:
            reg.gauge("serving_stream_active",
                      "streaming requests currently in flight").set(
                          self.stream_active)

    # ------------------------------------------------ overload control
    def on_watchdog_stall(self, label: str):
        """One step attempt ran past its watchdog budget."""
        self.watchdog_stalls += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_watchdog_stalls_total",
                        "compiled-step attempts over the watchdog "
                        "latency budget").inc(step=label)

    def on_step_retry(self, label: str):
        """One bounded-retry attempt after a stall or step exception."""
        self.step_retries += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_step_retries_total",
                        "compiled-step retries (stall or transient "
                        "exception)").inc(step=label)

    def on_degradation_level(self, level: int):
        """Degradation ladder moved to ``level`` (0 = normal)."""
        self.degradation_level = level
        reg = self._obs()
        if reg is not None:
            reg.gauge("serving_degradation_level",
                      "memory-pressure degradation ladder level "
                      "(0 normal .. 4 preempt)").set(level)

    def on_health(self, code: int):
        """Engine health gauge (0 serving / 1 degraded / 2 failed)."""
        self.health_state = code
        reg = self._obs()
        if reg is not None:
            reg.gauge("serving_health_state",
                      "engine health (0 serving / 1 degraded / "
                      "2 failed)").set(code)

    def on_kv_cache_config(self, dtype_code: int, scale_bytes: int):
        """Engine construction reports its KV-pool storage format:
        ``dtype_code`` per kernels.kv_quant.KV_DTYPE_CODES (0 fp32 /
        1 int8 / 2 fp8), ``scale_bytes`` = f32 absmax sidecar bytes per
        block per (k or v) pool side."""
        self.kv_cache_dtype_code = int(dtype_code)
        self.kv_quant_scale_bytes = int(scale_bytes)
        reg = self._obs()
        if reg is not None:
            reg.gauge("serving_kv_cache_dtype",
                      "KV-pool storage dtype code (0 fp32 / 1 int8 / "
                      "2 fp8)").set(self.kv_cache_dtype_code)
            reg.gauge("kv_quant_scale_bytes",
                      "per-block f32 absmax scale sidecar bytes of one "
                      "quantized KV pool side (0 unquantized)").set(
                          self.kv_quant_scale_bytes)

    def on_decode_iteration(self, active: int, batch_size: int,
                            cache_utilization: float):
        self.decode_iterations += 1
        occ = active / batch_size if batch_size else 0.0
        self.last_batch_occupancy = occ
        self.last_cache_utilization = cache_utilization
        self._occupancy_sum += occ
        self._cache_util_sum += cache_utilization
        self._gauge_samples += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("serving_decode_iterations_total",
                        "decode loop iterations").inc()
            reg.gauge("serving_batch_occupancy",
                      "active slots / batch size, last iteration").set(occ)
            reg.gauge("serving_cache_utilization",
                      "paged KV cache pages in use, last iteration").set(
                          cache_utilization)

    # --------------------------------------------------------- export
    def _span(self, name: str, start_ns: int, end_ns: int,
              category: str = "serving"):
        if not start_ns or end_ns < start_ns:
            return
        self._spans.append((name, start_ns, end_ns, category))
        # mirror into a live profiler session, if one is recording —
        # request spans then interleave with the framework's host
        # ranges and XLA device lanes in ONE chrome trace
        from .. import profiler

        if profiler.current_profiler() is not None:
            profiler.record_host_range(name, start_ns, end_ns,
                                       category=category)

    def as_dict(self) -> dict:
        n = max(self._gauge_samples, 1)
        return {
            "counters": {
                "requests_submitted": self.submitted,
                "requests_rejected": self.rejected,
                "requests_completed": self.completed,
                "requests_timed_out": self.timed_out,
                "requests_failed": self.failed,
                "preemptions": self.preempted,
                "tokens_generated": self.tokens_generated,
                "decode_iterations": self.decode_iterations,
                "prefills": self.prefills,
                "prefix_cache_hits": self.prefix_cache_hits,
                "prefix_cache_misses": self.prefix_cache_misses,
                "prefix_cache_evictions": self.prefix_cache_evictions,
                "prefill_chunks": self.prefill_chunks,
                "requests_shed": self.shed,
                "goodput_tokens": self.goodput_tokens,
                "watchdog_stalls": self.watchdog_stalls,
                "step_retries": self.step_retries,
                "spec_tokens_drafted": self.spec_tokens_drafted,
                "spec_tokens_accepted": self.spec_tokens_accepted,
            },
            "gauges": {
                "degradation_level": self.degradation_level,
                "health_state": self.health_state,
                "spec_accept_rate": round(self.spec_accept_rate(), 4),
                "stream_active": self.stream_active,
                "batch_occupancy": self.last_batch_occupancy,
                "batch_occupancy_avg": round(self._occupancy_sum / n, 4),
                "cache_utilization": self.last_cache_utilization,
                "cache_utilization_avg": round(
                    self._cache_util_sum / n, 4),
                "prefix_cached_token_ratio": round(
                    self._cached_tokens_sum
                    / max(self._prompt_tokens_sum, 1), 4),
                "serving_kv_cache_dtype": self.kv_cache_dtype_code,
                "kv_quant_scale_bytes": self.kv_quant_scale_bytes,
            },
            "requests": {rid: t.to_dict()
                         for rid, t in self.requests.items()},
        }

    def export_chrome(self, path: str) -> str:
        """Standalone chrome://tracing JSON of the request spans (use a
        live ``paddle_tpu.profiler.Profiler`` session instead to merge
        them with host/device lanes)."""
        events = [{"name": name, "cat": cat, "ph": "X",
                   "ts": start / 1000.0, "dur": (end - start) / 1000.0,
                   "pid": 0, "tid": 0}
                  for name, start, end, cat in self._spans]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        return path
