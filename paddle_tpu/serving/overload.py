# lint-tpu: disable-file=L004 -- serving-layer host-side control plane
# (like engine.py); new backend code belongs under core/ ops/ kernels/
"""Overload control for the serving engine: load shedding, a KV
memory-pressure degradation ladder, and a hung-step watchdog
(PAPERS.md: Sarathi/vLLM-tradition graceful degradation — README
"Overload control & graceful degradation").

Three cooperating mechanisms, all host-side (nothing here touches a
traced program, so the H106 no-host-work and no-retrace contracts are
untouched):

* **Load shedding** (:class:`AdmissionController`): at ``submit()``
  time, estimate the candidate's TTFT from the queue depth, the pending
  prefill tokens ahead of it, and EWMAs of the compiled chunk/decode
  step latencies.  When the OPTIMISTIC estimate already busts
  ``deadline_s``, retire the request immediately with
  ``finish_reason="shed"`` — a cheap rejection at admission beats a
  guaranteed timeout after burning prefill compute.  Sheds never fire
  while the EWMAs are cold (a fresh engine admits everything).

* **Degradation ladder** (:class:`DegradationLadder`): high/low
  watermarks with hysteresis over the pool's used fraction
  (free + parked blocks both count as headroom, matching
  ``BlockKVPool.num_free``).  Strictly above the high watermark the
  engine walks one level per iteration: evict parked prefix-cache blocks → shrink
  the effective prefill token budget to one chunk per iteration → pause
  admissions → preempt the youngest/lowest-priority running request.
  Below the low watermark it unwinds one level per iteration.  Every
  transition is a gauge (``serving_degradation_level``) and a log line.

* **Step watchdog** (:class:`StepWatchdog`): wraps each host-side call
  into the compiled prefill/decode steps with a monotonic-clock budget
  (``watchdog_budget_mult`` × the step's EWMA latency, floored by
  ``watchdog_floor_s`` so the first-call compile never trips it).  A
  stall or a transient step exception gets bounded retries with
  exponential backoff — the compiled steps are pure functions of their
  inputs, so a retry recomputes the identical result from the identical
  operands — after which the engine is quarantined: ``DEGRADED`` when
  it still produces results (slow), ``FAILED`` when retries exhaust on
  exceptions (:class:`EngineQuarantined` propagates out of ``step()``).
  ``DEGRADED`` self-heals after ``health_recovery_steps`` consecutive
  in-budget steps; ``FAILED`` needs an explicit ``Engine.revive()``.
"""
from __future__ import annotations

import logging
import math
import time
from typing import Callable, List, Optional, Tuple

log = logging.getLogger("paddle_tpu.serving")

# engine health states (Engine.health()["state"])
SERVING = "serving"
DEGRADED = "degraded"
FAILED = "failed"

_HEALTH_CODE = {SERVING: 0, DEGRADED: 1, FAILED: 2}

# degradation-ladder levels, walked one step per engine iteration
LADDER_LEVELS = ("normal", "evict_cache", "shrink_prefill",
                 "pause_admissions", "preempt")


class EngineQuarantined(RuntimeError):
    """The step watchdog exhausted its bounded retries on step
    exceptions: the engine is quarantined FAILED and refuses work until
    ``Engine.revive()``."""


class LatencyEWMA:
    """Exponentially-weighted moving average of a step latency.

    The FIRST observation is recorded separately as ``compile_s`` and
    kept out of the average — it is dominated by XLA compilation and
    would otherwise poison both the TTFT estimate (over-shedding) and
    the watchdog budget for the engine's whole lifetime."""

    def __init__(self, alpha: float = 0.2):
        self.alpha = alpha
        self.value: Optional[float] = None
        self.compile_s: Optional[float] = None
        self.samples = 0

    def observe(self, dt: float):
        if self.compile_s is None:
            self.compile_s = dt
            return
        self.samples += 1
        self.value = dt if self.value is None else (
            self.alpha * dt + (1.0 - self.alpha) * self.value)

    @property
    def warmed(self) -> bool:
        return self.value is not None


class EngineHealth:
    """SERVING / DEGRADED / FAILED state machine fed by the watchdogs.

    DEGRADED (stalls detected, engine still producing) self-heals after
    ``recovery_steps`` consecutive in-budget steps; FAILED (retries
    exhausted on step exceptions) is sticky until ``revive()``."""

    def __init__(self, metrics=None, recovery_steps: int = 3):
        self.state = SERVING
        self.recovery_steps = recovery_steps
        self.last_error: Optional[str] = None
        self._clean = 0
        self._metrics = metrics
        self._publish()

    def _publish(self):
        if self._metrics is not None:
            self._metrics.on_health(_HEALTH_CODE[self.state])

    def _transition(self, new: str, why: str):
        if new != self.state:
            log.warning("engine health %s -> %s (%s)",
                        self.state, new, why)
            self.state = new
            self._publish()

    def on_stall(self, label: str, dt: float, budget: float):
        self._clean = 0
        if self.state != FAILED:
            self._transition(
                DEGRADED, f"{label} stalled {dt:.3f}s > {budget:.3f}s")

    def on_failure(self, label: str, error: BaseException):
        self.last_error = f"{type(error).__name__}: {error}"
        self._clean = 0
        self._transition(FAILED, f"{label}: {self.last_error}")

    def on_clean_step(self):
        if self.state == DEGRADED:
            self._clean += 1
            if self._clean >= self.recovery_steps:
                self._transition(
                    SERVING, f"{self._clean} consecutive in-budget steps")
        else:
            self._clean = 0

    def revive(self):
        """Operator override: clear FAILED/DEGRADED back to SERVING."""
        self.last_error = None
        self._clean = 0
        self._transition(SERVING, "revive()")

    @property
    def failed(self) -> bool:
        return self.state == FAILED


class StepWatchdog:
    """Monotonic-clock watchdog + bounded retry around ONE compiled
    step entry point (decode or chunked prefill).

    Timing wraps the host-side dispatch only — no synchronization is
    added inside a traced program, so registered step jaxprs stay
    H106-clean.  The chaos serving-step hook fires INSIDE the timed
    window (before the device call) so injected delays register as
    stalls and injected exceptions exercise the retry path."""

    def __init__(self, label: str, ewma: LatencyEWMA, health: EngineHealth,
                 metrics, *, budget_mult: float, floor_s: float,
                 max_retries: int, backoff_s: float):
        self.label = label
        self.ewma = ewma
        self.health = health
        self.metrics = metrics
        self.budget_mult = budget_mult
        self.floor_s = floor_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.stalls = 0
        self.retries = 0

    def budget_s(self) -> float:
        """Per-attempt latency budget: a multiple of the EWMA, floored
        generously so the first-call XLA compile never trips it."""
        if not self.ewma.warmed:
            return self.floor_s
        return max(self.floor_s, self.budget_mult * self.ewma.value)

    def call(self, fn: Callable, *args):
        """Run ``fn(*args)`` under the budget with bounded retries.

        Stall (slow but successful) → count it, mark the engine
        DEGRADED, retry; if every attempt stalls, keep the LAST result
        (degrade, don't fail — the step did complete).  Exception →
        retry with exponential backoff; exhausted → quarantine FAILED
        and raise :class:`EngineQuarantined`.  Retries re-dispatch the
        same pure compiled program on the same operands: identical
        result, jit-cache hit, zero retraces."""
        from ..observability import RetraceError
        from ..resilience import chaos

        last_error: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            t0 = time.monotonic()
            try:
                chaos.maybe_fail_serving_step(self.label)
                out = fn(*args)
            except RetraceError:
                raise       # contract violation, not a transient fault
            except Exception as e:  # noqa: BLE001 — bounded retry
                last_error = e
                self.retries += 1
                self.metrics.on_step_retry(self.label)
                log.warning("%s attempt %d/%d failed: %s", self.label,
                            attempt + 1, self.max_retries + 1, e)
                continue
            dt = time.monotonic() - t0
            budget = self.budget_s()
            if dt > budget:
                self.stalls += 1
                self.metrics.on_watchdog_stall(self.label)
                self.health.on_stall(self.label, dt, budget)
                if attempt < self.max_retries:
                    self.retries += 1
                    self.metrics.on_step_retry(self.label)
                    continue
                return out      # every attempt stalled: degrade, keep it
            self.ewma.observe(dt)
            self.health.on_clean_step()
            return out
        self.health.on_failure(self.label, last_error)
        raise EngineQuarantined(
            f"{self.label}: {self.max_retries + 1} attempts failed; "
            f"engine quarantined FAILED (last: {last_error!r})"
        ) from last_error


class DegradationLadder:
    """Hysteresis watermarks over KV-pool pressure driving the explicit
    degradation ladder (module docstring).  One level per engine
    iteration in either direction — escalation is deliberate, and the
    unwind retraces the same rungs."""

    def __init__(self, metrics, *, high: float, low: float):
        if not 0.0 <= low <= high <= 1.0:
            raise ValueError(
                f"watermarks need 0 <= low <= high <= 1, got "
                f"low={low} high={high}")
        self.high = high
        self.low = low
        self.level = 0
        self.metrics = metrics
        # (engine iteration ordinal, new level) — tests assert ordering
        self.transitions: List[Tuple[int, int]] = []
        self._ticks = 0

    @property
    def level_name(self) -> str:
        return LADDER_LEVELS[self.level]

    @property
    def admissions_paused(self) -> bool:
        return self.level >= LADDER_LEVELS.index("pause_admissions")

    def effective_prefill_budget(self, configured: int) -> int:
        """Shrink the per-iteration prefill token budget to ONE token
        at or above the shrink level — each chunk still advances a full
        ``chunk_tokens`` (fixed compiled shape), but only one chunk runs
        per iteration, keeping decode responsive under pressure."""
        if self.level >= LADDER_LEVELS.index("shrink_prefill"):
            return 1
        return configured

    def _set_level(self, level: int, pressure: float):
        log.warning(
            "degradation ladder %s -> %s (kv pressure %.2f, "
            "high=%.2f low=%.2f)", self.level_name,
            LADDER_LEVELS[level], pressure, self.high, self.low)
        self.level = level
        self.transitions.append((self._ticks, level))
        self.metrics.on_degradation_level(level)

    def tick(self, engine) -> int:
        """One hysteresis step against current pool pressure, applying
        the newly-reached level's action.  Returns the level."""
        self._ticks += 1
        # BYTE-denominated pressure: used KV bytes over the pool's byte
        # capacity (scale sidecars included), so the watermark is a
        # statement about HBM, not block counts.  Two engines sized
        # from the same kv_pool_bytes budget at different KV dtypes see
        # comparable pressure per resident byte — the quantized one
        # fits ~4x the blocks, so the SAME burst crosses the high
        # watermark later at int8 than at fp32 (dtype-aware ladder,
        # ISSUE 20).
        pressure = engine.pool.byte_utilization()
        # STRICTLY above the high watermark: the default high=1.0 can
        # never be exceeded (a fully-referenced pool is the engine's
        # normal preemption-managed regime, and tiny test pools live
        # there), so the ladder engages only when a deployment sets
        # kv_high_watermark < 1.0
        if pressure > self.high and self.level < len(LADDER_LEVELS) - 1:
            self._set_level(self.level + 1, pressure)
        elif pressure < self.low and self.level > 0:
            self._set_level(self.level - 1, pressure)
        if self.level >= LADDER_LEVELS.index("evict_cache"):
            # parked prefix blocks are reclaimable headroom; under
            # pressure give them back eagerly instead of lazily via
            # allocate()'s LRU fallback
            engine.pool.evict_parked()
        if self.level >= LADDER_LEVELS.index("preempt") \
                and len(engine.scheduler.running) > 1:
            # shed running work, lowest-priority/youngest first; never
            # the sole running request (preempting it frees nothing
            # durable — it would bounce straight back)
            victim = engine.scheduler.pick_victim()
            if victim is not None:
                engine._preempt(victim)
        return self.level


class OverloadController:
    """Facade owned by the engine bundling the EWMAs, admission
    controller, ladder, health state, and the two step watchdogs."""

    def __init__(self, config, metrics):
        self.config = config
        self.metrics = metrics
        self.chunk_ewma = LatencyEWMA()
        self.decode_ewma = LatencyEWMA()
        self.health = EngineHealth(
            metrics, recovery_steps=config.health_recovery_steps)
        self.ladder = DegradationLadder(
            metrics, high=config.kv_high_watermark,
            low=config.kv_low_watermark)
        # a named replica (ServingConfig(name=...), fleet routing) tags
        # its step labels so chaos plans and metrics can target ONE
        # engine; the default stays the bare single-engine label
        tag = f"@{config.name}" if getattr(config, "name", "") else ""
        self.prefill_watchdog = StepWatchdog(
            f"serving::prefill_step{tag}", self.chunk_ewma, self.health,
            metrics, budget_mult=config.watchdog_budget_mult,
            floor_s=config.watchdog_floor_s,
            max_retries=config.step_max_retries,
            backoff_s=config.step_retry_backoff_s)
        self.decode_watchdog = StepWatchdog(
            f"serving::decode_step{tag}", self.decode_ewma, self.health,
            metrics, budget_mult=config.watchdog_budget_mult,
            floor_s=config.watchdog_floor_s,
            max_retries=config.step_max_retries,
            backoff_s=config.step_retry_backoff_s)
        self._tag = tag

    def extra_watchdog(self, kind: str) -> StepWatchdog:
        """A watchdog for an ADDITIONAL compiled step entry point (the
        speculative draft/verify steps) with its OWN LatencyEWMA.
        Sharing one EWMA across two programs would record the second
        program's first-call compile as a real latency sample — the
        exact poisoning the per-EWMA ``compile_s`` carve-out exists to
        prevent — inflating the watchdog budget and the TTFT estimate
        (over-shedding) for the engine's whole lifetime."""
        return StepWatchdog(
            f"serving::{kind}{self._tag}", LatencyEWMA(), self.health,
            self.metrics, budget_mult=self.config.watchdog_budget_mult,
            floor_s=self.config.watchdog_floor_s,
            max_retries=self.config.step_max_retries,
            backoff_s=self.config.step_retry_backoff_s)

    # ------------------------------------------------------ load shedding
    def can_estimate(self) -> bool:
        """Shedding only fires once the chunk EWMA has a real (post-
        compile) sample: a fresh engine has no basis for an estimate and
        must admit everything (cold-start safety)."""
        return self.config.enable_load_shedding and self.chunk_ewma.warmed

    def estimate_ttft_s(self, engine, prompt) -> float:
        """Optimistic TTFT estimate for a CANDIDATE prompt arriving now:
        every prefill token ahead of it (waiting queue + mid-prefill
        remainders) plus its own uncached tokens, paced by the per-
        iteration prefill budget with one decode step interleaved per
        iteration.  Optimistic by design — it ignores decode-slot
        contention and future arrivals — so a shed only happens when
        even the best case busts the deadline."""
        C = engine.chunk_tokens
        chunk_s = self.chunk_ewma.value
        decode_s = self.decode_ewma.value or 0.0
        pending = engine.pending_prefill_tokens()
        matched, _, _ = engine.pool.admission_plan(prompt, extra_tokens=0)
        own = max(1, len(prompt) - len(matched) * engine.pool.block_size)
        chunks = math.ceil(pending / C) + math.ceil(own / C)
        budget = self.ladder.effective_prefill_budget(
            self.config.prefill_token_budget or C)
        chunks_per_iter = max(1, budget // C)
        iters = math.ceil(chunks / chunks_per_iter)
        return chunks * chunk_s + iters * decode_s

    def should_shed(self, engine, prompt,
                    deadline_s: Optional[float]) -> bool:
        if deadline_s is None or not self.can_estimate():
            return False
        est = self.estimate_ttft_s(engine, prompt)
        shed = est > deadline_s * self.config.shed_safety_factor
        if shed:
            log.info("shedding request: est TTFT %.3fs > deadline %.3fs",
                     est, deadline_s)
        return shed

    # ------------------------------------------------------------- health
    def snapshot(self, engine) -> dict:
        """``Engine.health()`` payload — a host-side dict, cheap enough
        for a load balancer to poll every second."""
        return {
            "state": self.health.state,
            "last_error": self.health.last_error,
            "degradation_level": self.ladder.level,
            "degradation_level_name": self.ladder.level_name,
            "admissions_paused": self.ladder.admissions_paused,
            "watchdog_stalls": (self.prefill_watchdog.stalls
                                + self.decode_watchdog.stalls),
            "step_retries": (self.prefill_watchdog.retries
                             + self.decode_watchdog.retries),
            "ewma_chunk_s": self.chunk_ewma.value,
            "ewma_decode_s": self.decode_ewma.value,
            "queue_depth": len(engine.scheduler.waiting),
            "kv_pressure": engine.pool.byte_utilization(),
            "kv_dtype": engine.pool.kv_dtype_tag,
            "kv_used_bytes": engine.pool.used_bytes(),
            "kv_capacity_bytes": engine.pool.capacity_bytes(),
        }


__all__ = ["SERVING", "DEGRADED", "FAILED", "LADDER_LEVELS",
           "EngineQuarantined", "LatencyEWMA", "EngineHealth",
           "StepWatchdog", "DegradationLadder", "OverloadController"]
