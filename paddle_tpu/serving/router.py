# lint-tpu: disable-file=L004 -- serving-layer host-side control plane
# (like engine.py/overload.py); new backend code belongs under core/
# ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Serving fleet router: prefix-aware, load-aware placement over N
engine replicas (README "Serving fleet & router"; ROADMAP item 1 —
"one plan, many hosts, many replicas").

A :class:`Router` owns N :class:`~paddle_tpu.serving.engine.Engine`
replicas and places every ``submit()`` by a SCORED policy:

* **prefix-cache affinity** — the prompt's block hashes are chained
  exactly as ``BlockKVPool.match_prefix`` chains them (same
  ``hash_chain``), then walked against each replica's
  ``pool.prefix_summary()`` hash set, stopping at the first miss: the
  leading-match count × block_size is the expected cached-token count
  on that replica.  Requests sharing a system prompt therefore
  gravitate to the replica already holding its blocks and re-prefill
  only their unique tails.
* **load** — the same public signals ``Engine.stats()``/``health()``
  export: ``pending_prefill_tokens`` (prefill backlog), queue depth,
  the compile-excluded chunk/decode latency EWMAs, and the degradation
  level.  Cold EWMAs fall back to a constant cost-per-token, so a
  fresh fleet scores purely by token counts (deterministic).

The placement cost (lower wins; README documents the same formula)::

    cost(r) = (pending_prefill_tokens(r) + uncached_tokens(r, prompt))
                  * t_prefill_token(r)
            + queue_depth(r) * t_decode(r)
            + penalty(r)          # degradation ladder + DEGRADED health

Ties break by a SEEDED rng — the only randomness in placement, so the
same trace + seed reproduces a byte-identical placement log.  Policy
``"round_robin"`` ignores scoring (the bench baseline).

**Global admission control**: the router sheds a hopeless-deadline
request at the FLEET boundary — when every healthy replica's (warmed)
TTFT estimate busts the deadline, the request is retired with
``finish_reason="shed"`` before ANY replica spends queue space or KV
blocks.  Router sheds globally before engines shed locally; the
per-engine shed remains as the backstop for load that arrives between
estimates.

**Replica lifecycle**: DEGRADED replicas keep serving but pay a score
penalty (deprioritized, not abandoned); a replica that quarantines
FAILED (:class:`EngineQuarantined` out of ``step()``) is drained — its
stranded requests release their KV blocks and are RESUBMITTED to
healthy replicas with their remaining deadline budget, re-prefilling
only what the target replica's prefix cache does not already hold.
Greedy decode makes the retry token-exact with an undisturbed run.
When no healthy replica remains, stranded requests retire with
``finish_reason="error"`` — explicitly finished, never lost.

Everything here is host-side control plane: no device work, no traced
code, ``time.monotonic`` only (deadlines — hazard H111), and the
engines' H106/no-retrace contracts are untouched.
"""
from __future__ import annotations

import itertools
import logging
import random
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import registry as _obsreg
from .engine import Engine
from .overload import DEGRADED, FAILED, SERVING, EngineQuarantined
from .scheduler import FINISHED, AdmissionError, Request

log = logging.getLogger("paddle_tpu.serving")

ROUTER_POLICIES = ("affinity", "round_robin")

# cost-per-prefill-token when a replica's EWMAs are cold: the VALUE is
# arbitrary (every cold replica uses the same one, so relative order is
# by token counts alone) — it only keeps cold and warm costs on one axis
_COLD_SEC_PER_TOKEN = 1e-3
# score penalty per degradation-ladder level / for DEGRADED health, in
# prefill-token equivalents (scaled by the replica's cost-per-token)
_LADDER_PENALTY_TOKENS = 256
_DEGRADED_PENALTY_TOKENS = 1024
# per-replica bound on remembered in-flight placement hashes (the
# sticky-before-registered affinity signal); oldest forgotten first
_PENDING_HASH_CAP = 1024


class RouterMetrics:
    """Fleet-level counters, mirrored as ``router_*`` into the shared
    observability registry (the ServingMetrics pattern: handles are
    looked up per event so ``registry.clear()`` never strands a
    mirror)."""

    def __init__(self):
        self.submitted = 0
        self.rejected = 0
        self.shed_global = 0
        self.resubmits = 0
        self.quarantines = 0
        self.placements: Dict[str, int] = {}
        self._affinity_tokens_sum = 0   # expected cached at placement
        self._prompt_tokens_sum = 0

    @staticmethod
    def _obs():
        return _obsreg.get_registry() if _obsreg.enabled() else None

    def on_submit(self):
        self.submitted += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("router_requests_submitted_total",
                        "requests submitted to the fleet router").inc()

    def on_reject(self):
        self.rejected += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("router_requests_rejected_total",
                        "requests no replica would admit").inc()

    def on_place(self, replica: str, affinity_tokens: int,
                 prompt_tokens: int):
        self.placements[replica] = self.placements.get(replica, 0) + 1
        self._affinity_tokens_sum += affinity_tokens
        self._prompt_tokens_sum += prompt_tokens
        reg = self._obs()
        if reg is not None:
            reg.counter("router_placements_total",
                        "requests placed, by replica").inc(replica=replica)
            reg.gauge("router_affinity_token_ratio",
                      "prompt tokens expected cached at placement, "
                      "cumulative ratio").set(
                          self._affinity_tokens_sum
                          / max(self._prompt_tokens_sum, 1))

    def on_shed_global(self):
        self.shed_global += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("router_requests_shed_global_total",
                        "requests shed at the fleet boundary (every "
                        "healthy replica's estimated TTFT busts the "
                        "deadline)").inc()

    def on_quarantine(self, replica: str):
        self.quarantines += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("router_replica_quarantines_total",
                        "replicas drained after a FAILED quarantine"
                        ).inc(replica=replica)

    def on_resubmit(self, replica: str):
        self.resubmits += 1
        reg = self._obs()
        if reg is not None:
            reg.counter("router_requests_resubmitted_total",
                        "stranded requests resubmitted after a replica "
                        "failure, by NEW replica").inc(replica=replica)

    def set_fleet_gauges(self, serving: int, total: int,
                         queue_depth: int, pending_tokens: int):
        reg = self._obs()
        if reg is not None:
            reg.gauge("router_serving_replicas",
                      "replicas in SERVING health").set(serving)
            reg.gauge("router_replicas", "replicas owned").set(total)
            reg.gauge("router_queue_depth",
                      "waiting requests across the fleet").set(queue_depth)
            reg.gauge("router_pending_prefill_tokens",
                      "prefill backlog across the fleet").set(
                          pending_tokens)

    def as_dict(self) -> dict:
        return {
            "requests_submitted": self.submitted,
            "requests_rejected": self.rejected,
            "requests_shed_global": self.shed_global,
            "requests_resubmitted": self.resubmits,
            "replica_quarantines": self.quarantines,
            "placements": dict(self.placements),
            "affinity_token_ratio": round(
                self._affinity_tokens_sum
                / max(self._prompt_tokens_sum, 1), 4),
        }


@dataclass
class _Replica:
    name: str
    engine: Engine
    # chain hashes of prompts PLACED here whose prefill has not
    # necessarily registered yet (hex, insertion-ordered, bounded):
    # the affinity walk credits them alongside the pool's registered
    # index, so a burst of same-prefix requests sticks to ONE replica
    # from the first placement instead of scattering until the first
    # prefill completes and registers the prefix
    pending_hashes: "OrderedDict[str, None]" = field(
        default_factory=OrderedDict)


@dataclass
class _Tracked:
    """Router-side record of one placed request: everything needed to
    RESUBMIT it elsewhere if its replica dies, plus the live handle."""

    replica: str
    handle: Request
    kwargs: dict = field(default_factory=dict)
    resubmits: int = 0


class Router:
    """Engine-shaped front door over N replicas: ``submit`` / ``step``
    / ``run_until_complete`` / ``generate`` / ``health`` / ``stats``
    mirror :class:`Engine`, so anything accepting an engine (notably
    :class:`~paddle_tpu.serving.endpoint.Endpoint`) accepts a router.

    Parameters
    ----------
    replicas: the engines to fan over (at least one; equal block_size
        everywhere, since prefix affinity chains hashes per block).
        Unnamed engines (``ServingConfig(name="")``) get positional
        names ``replica-<i>`` for logs/metrics.
    policy: ``"affinity"`` (scored placement, the default) or
        ``"round_robin"`` (the bench baseline).
    seed: placement tie-break rng seed — the ONLY randomness.
    affinity_weight: how many prefill-tokens of load one cached token
        outweighs in the placement score (see :meth:`_cost`) — higher
        consolidates prompt families harder before spilling on load.
    enable_global_shedding: shed hopeless-deadline requests at the
        fleet boundary (before any replica spends KV).
    shed_safety_factor: shed when min estimated TTFT > deadline ×
        factor (mirrors ``ServingConfig.shed_safety_factor``).
    """

    def __init__(self, replicas: Sequence[Engine], *,
                 policy: str = "affinity", seed: int = 0,
                 affinity_weight: float = 3.0,
                 enable_global_shedding: bool = True,
                 shed_safety_factor: float = 1.0):
        if not replicas:
            raise ValueError("Router needs at least one Engine replica")
        if policy not in ROUTER_POLICIES:
            raise ValueError(f"unknown policy {policy!r}; choose from "
                             f"{ROUTER_POLICIES}")
        sizes = {e.config.block_size for e in replicas}
        if len(sizes) != 1:
            raise ValueError(
                "prefix-affinity routing chains hashes per block, so "
                f"every replica needs ONE block_size; got {sorted(sizes)}")
        self.replicas: List[_Replica] = []
        for i, eng in enumerate(replicas):
            name = eng.config.name or f"replica-{i}"
            if any(r.name == name for r in self.replicas):
                raise ValueError(f"duplicate replica name {name!r}")
            self.replicas.append(_Replica(name, eng))
        self.policy = policy
        self.seed = seed
        self.affinity_weight = affinity_weight
        self.enable_global_shedding = enable_global_shedding
        self.shed_safety_factor = shed_safety_factor
        self.metrics = RouterMetrics()
        self._rng = random.Random(seed)     # tie-breaks ONLY
        self._rr_next = 0                   # round-robin cursor
        self._ids = itertools.count()
        self._tracked: Dict[str, _Tracked] = {}
        self._finished: Dict[str, Request] = {}
        # one line per placement decision; deterministic for a given
        # trace + seed on a fresh fleet (tests pin byte-identity)
        self.placement_log: List[str] = []

    # ---------------------------------------------------------- scoring
    def _healthy(self) -> List[_Replica]:
        return [r for r in self.replicas
                if not r.engine.overload.health.failed]

    def _affinity_tokens(self, rep: _Replica, prompt: np.ndarray,
                         chain_hex: List[str]) -> int:
        """Expected cached-token count for ``prompt`` on ``rep``:
        leading chain hashes present in the replica's prefix-index
        summary (the stop-at-first-miss walk ``match_prefix`` does) OR
        among prompts already PLACED there (in-flight prefills register
        their prefix on completion, so crediting them keeps a burst of
        same-prefix arrivals on one replica instead of scattering until
        the first registration lands).  Capped at prompt_len - 1 — the
        last token is always recomputed (its logits row is the first
        generated token)."""
        idx = set(rep.engine.pool.prefix_summary()["hashes"])
        n = 0
        for h in self._replica_chain(rep, chain_hex):
            if h not in idx and h not in rep.pending_hashes:
                break
            n += 1
        bs = rep.engine.pool.block_size
        return min(n * bs, int(prompt.size) - 1) if n else 0

    def _cost(self, rep: _Replica, prompt: np.ndarray,
              affinity_tokens: int) -> float:
        """Placement cost in estimated seconds (module docstring): the
        prefill work queued ahead plus this prompt's UNCACHED share,
        decode contention, and lifecycle penalties, minus a weighted
        affinity bonus.  Cold EWMAs use one shared constant so a fresh
        fleet orders by token counts.

        The bonus is ``affinity_weight × cached tokens`` (in token-
        seconds) ON TOP of the uncached-share saving: a cache hit is
        worth more than the prefill seconds it skips — it spends no KV
        blocks on duplicate prefixes and keeps a tenant's prompt family
        consolidated on one replica instead of seeding copies fleet-wide
        every time transient load tips the balance.  A replica only
        loses a high-affinity request when its load exceeds the bonus
        (~weight × prefix length in prefill tokens) — graceful spill,
        not ping-ponging."""
        eng = rep.engine
        ov = eng.overload
        per_tok = (ov.chunk_ewma.value / eng.chunk_tokens
                   if ov.chunk_ewma.warmed else _COLD_SEC_PER_TOKEN)
        t_decode = ov.decode_ewma.value if ov.decode_ewma.warmed else 0.0
        uncached = max(1, int(prompt.size) - affinity_tokens)
        cost = (eng.pending_prefill_tokens() + uncached) * per_tok
        cost += len(eng.scheduler.waiting) * t_decode
        cost -= self.affinity_weight * affinity_tokens * per_tok
        penalty = ov.ladder.level * _LADDER_PENALTY_TOKENS
        if ov.health.state == DEGRADED:
            penalty += _DEGRADED_PENALTY_TOKENS
        return cost + penalty * per_tok

    def _chain_hex(self, prompt: np.ndarray) -> Dict[str, List[str]]:
        """The prompt's chained block hashes (hex), keyed by the pool's
        KV dtype tag.  Hashing is pure content chaining — identical on
        every replica with equal block_size AND equal KV dtype — but
        the chains are seeded per dtype (an int8 pool must never match
        an fp32-registered block), so a mixed-dtype fleet needs one
        chain per distinct tag.  Computed once per tag per prompt."""
        chains: Dict[str, List[str]] = {}
        for rep in self.replicas:
            pool = rep.engine.pool
            tag = getattr(pool, "kv_dtype_tag", "fp32")
            if tag not in chains:
                chains[tag] = [h.hex() for h in pool.hash_chain(prompt)]
        return chains

    @staticmethod
    def _replica_chain(rep: _Replica,
                       chain_hex: Dict[str, List[str]]) -> List[str]:
        """The chain matching ``rep``'s pool dtype (empty if absent —
        a replica added after chains were computed scores no affinity
        rather than walking a foreign-dtype chain)."""
        tag = getattr(rep.engine.pool, "kv_dtype_tag", "fp32")
        return chain_hex.get(tag, [])

    def _rank(self, prompt: np.ndarray, chain_hex: Dict[str, List[str]]
              ) -> List[Tuple[_Replica, int, float]]:
        """Healthy replicas ranked best-first: ``(replica, affinity
        tokens, cost)``.  Equal-cost groups are shuffled by the seeded
        tie-break rng (the only randomness in placement)."""
        healthy = self._healthy()
        if self.policy == "round_robin":
            order = [healthy[(self._rr_next + i) % len(healthy)]
                     for i in range(len(healthy))]
            self._rr_next += 1
            return [(r, 0, 0.0) for r in order]
        scored = []
        for r in healthy:
            aff = self._affinity_tokens(r, prompt, chain_hex)
            scored.append((r, aff, self._cost(r, prompt, aff)))
        # group by rounded cost; seeded shuffle WITHIN a tie group only
        scored.sort(key=lambda t: round(t[2], 9))
        out: List[Tuple[_Replica, int, float]] = []
        i = 0
        while i < len(scored):
            j = i + 1
            while j < len(scored) and \
                    round(scored[j][2], 9) == round(scored[i][2], 9):
                j += 1
            group = scored[i:j]
            if len(group) > 1:
                self._rng.shuffle(group)
            out.extend(group)
            i = j
        return out

    # ----------------------------------------------------------- submit
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None, stop_sequences=None,
               tokenizer=None, request_id: Optional[str] = None,
               temperature: float = 0.0, do_sample: bool = False,
               top_k: int = 0, top_p: float = 1.0,
               seed: Optional[int] = None, sampling=None,
               on_token=None, token_deadline_s: Optional[float] = None,
               deadline_s: Optional[float] = None, priority: int = 0
               ) -> Request:
        """Place one request on the best replica (``Engine.submit``
        semantics: returns the handle; hopeless-deadline requests come
        back ``finish_reason="shed"``; raises ``AdmissionError`` when
        no replica will take it).  NOTE: on a replica failure the
        request is resubmitted elsewhere under the SAME request_id with
        a fresh handle — ``run_until_complete()``'s returned dict is
        the authoritative handle map."""
        healthy = self._healthy()
        if not healthy:
            self.metrics.on_reject()
            raise AdmissionError(
                f"all {len(self.replicas)} replicas quarantined FAILED; "
                "revive() one after operator intervention")
        p = np.asarray(
            prompt.numpy() if hasattr(prompt, "numpy") else prompt,
            np.int32).reshape(-1)
        rid = request_id or f"rq-{next(self._ids)}"
        kwargs = dict(max_new_tokens=max_new_tokens,
                      eos_token_id=eos_token_id,
                      stop_sequences=stop_sequences, tokenizer=tokenizer,
                      temperature=temperature, do_sample=do_sample,
                      top_k=top_k, top_p=top_p, seed=seed,
                      sampling=sampling, on_token=on_token,
                      token_deadline_s=token_deadline_s,
                      priority=priority)
        self.metrics.on_submit()
        # ---- global admission control: shed at the FLEET boundary
        # when every healthy replica's warmed estimate busts the
        # deadline — before any replica spends queue space or KV
        if self._should_shed_globally(p, deadline_s, healthy):
            req = Request(prompt=p, request_id=rid, deadline_s=deadline_s,
                          priority=priority,
                          max_new_tokens=max_new_tokens,
                          eos_token_id=eos_token_id)
            req.state = FINISHED
            req.finish_reason = "shed"
            self._finished[rid] = req
            self.metrics.on_shed_global()
            self.placement_log.append(f"{rid} -> SHED policy=global")
            log.info("router shed %s at the fleet boundary "
                     "(deadline %.3fs hopeless on every replica)",
                     rid, deadline_s)
            return req
        return self._place(rid, p, kwargs, deadline_s, resubmit_of=None)

    def _should_shed_globally(self, prompt: np.ndarray,
                              deadline_s: Optional[float],
                              healthy: List[_Replica]) -> bool:
        if deadline_s is None or not self.enable_global_shedding:
            return False
        estimates = []
        for rep in healthy:
            ov = rep.engine.overload
            if not ov.can_estimate():
                return False    # a cold replica might serve it: admit
            estimates.append(ov.estimate_ttft_s(rep.engine, prompt))
        return min(estimates) > deadline_s * self.shed_safety_factor

    def _place(self, rid: str, prompt: np.ndarray, kwargs: dict,
               deadline_s: Optional[float],
               resubmit_of: Optional[_Tracked]) -> Request:
        """Rank replicas and submit to the first that admits; the next
        candidates absorb per-replica backpressure (QueueFull etc.)."""
        last_err: Optional[Exception] = None
        chain_hex = self._chain_hex(prompt)
        for rep, aff, cost in self._rank(prompt, chain_hex):
            try:
                handle = rep.engine.submit(
                    prompt, request_id=rid, deadline_s=deadline_s,
                    **kwargs)
            except AdmissionError as e:
                last_err = e
                continue
            # remember the placement's chain hashes as in-flight
            # affinity (bounded, oldest forgotten): follow-ups sharing
            # the prefix stick here even before prefill registers it
            for h in self._replica_chain(rep, chain_hex):
                rep.pending_hashes.pop(h, None)
                rep.pending_hashes[h] = None
            while len(rep.pending_hashes) > _PENDING_HASH_CAP:
                rep.pending_hashes.popitem(last=False)
            tracked = resubmit_of or _Tracked(rep.name, handle, kwargs)
            tracked.replica = rep.name
            tracked.handle = handle
            tracked.kwargs = kwargs
            self._tracked[rid] = tracked
            tag = f" resubmit={tracked.resubmits}" \
                if tracked.resubmits else ""
            self.placement_log.append(
                f"{rid} -> {rep.name} policy={self.policy} aff={aff} "
                f"cost={cost:.6f}{tag}")
            self.metrics.on_place(rep.name, aff, int(prompt.size))
            if resubmit_of is not None:
                self.metrics.on_resubmit(rep.name)
            # an engine-level shed retires the handle instantly — pull
            # it through to the router's finished map right away
            if handle.state == FINISHED:
                self._drain_finished(rep)
            return handle
        self.metrics.on_reject()
        raise last_err if last_err is not None else AdmissionError(
            f"{rid}: no replica admitted the request")

    # ------------------------------------------------------------- step
    def step(self) -> bool:
        """One fleet iteration: step every healthy replica once,
        drain finished requests, and turn any FAILED quarantine into a
        drain-and-resubmit instead of a raised exception.  Returns True
        while any replica has work."""
        for rep in self.replicas:
            eng = rep.engine
            if eng.overload.health.failed:
                self._drain_replica(rep)
                continue
            if eng.has_work():
                try:
                    eng.step()
                except EngineQuarantined as e:
                    log.warning("router: replica %s quarantined (%s); "
                                "draining and resubmitting", rep.name, e)
                    self.metrics.on_quarantine(rep.name)
                    self._drain_replica(rep)
            self._drain_finished(rep)
        self._publish_gauges()
        return self.has_work()

    def has_work(self) -> bool:
        return any(r.engine.has_work() for r in self._healthy())

    def run_until_complete(self) -> Dict[str, Request]:
        """Drain the whole fleet; returns {request_id: Request} for
        every request finished during this drain — the AUTHORITATIVE
        handles (a failover resubmission supersedes the handle
        ``submit`` returned)."""
        while self.step():
            pass
        done, self._finished = self._finished, {}
        return done

    def generate(self, prompts, **submit_kwargs) -> List[np.ndarray]:
        """Batch convenience mirroring ``Engine.generate``: submit every
        prompt, drain, outputs (prompt + generated) in order."""
        reqs = [self.submit(p, **submit_kwargs) for p in prompts]
        done = self.run_until_complete()
        return [done[r.request_id].output_ids() for r in reqs]

    # ------------------------------------------------ replica lifecycle
    def _drain_finished(self, rep: _Replica):
        eng = rep.engine
        if not eng._finished:
            return
        for rid, req in eng._finished.items():
            self._finished[rid] = req
            t = self._tracked.get(rid)
            if t is not None:
                t.replica = rep.name
                t.handle = req
        eng._finished.clear()

    def _drain_replica(self, rep: _Replica):
        """Drain a FAILED replica: release every stranded request's KV
        blocks, clear its slots, and resubmit the requests to healthy
        replicas with their REMAINING deadline budget.  The retry
        recomputes from the prompt (greedy: token-exact) and re-prefills
        only what the target's prefix cache misses."""
        eng = rep.engine
        self._drain_finished(rep)
        rep.pending_hashes.clear()  # in-flight prefills died with it
        stranded = list(eng.scheduler.waiting) + list(eng.scheduler.running)
        if not stranded:
            return
        eng.scheduler.waiting.clear()
        eng.scheduler.running.clear()
        for i in range(len(eng._slots)):
            eng._slots[i] = None
        eng._block_tables[:] = 0
        eng._lengths[:] = 0
        eng._pending[:] = 0
        for req in stranded:
            eng.pool.free_request(req.request_id)
        log.warning("router: drained %d stranded request(s) from %s",
                    len(stranded), rep.name)
        for req in sorted(stranded, key=lambda r: r.ordinal):
            self._resubmit(req)

    def _resubmit(self, req: Request):
        rid = req.request_id
        tracked = self._tracked.get(rid)
        kwargs = tracked.kwargs if tracked is not None else dict(
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id)
        # remaining SLO budget on the monotonic clock: the failover
        # must not extend the caller's deadline
        deadline_s: Optional[float] = None
        if req.deadline_t is not None:
            deadline_s = req.deadline_t - time.monotonic()
            if deadline_s <= 0:
                self._retire_router_side(req, "timeout")
                return
        if not self._healthy():
            req.error = "all replicas quarantined FAILED"
            self._retire_router_side(req, "error")
            return
        if tracked is not None:
            tracked.resubmits += 1
        try:
            self._place(rid, req.prompt, kwargs, deadline_s,
                        resubmit_of=tracked)
        except AdmissionError as e:
            req.error = f"failover resubmission rejected: {e}"
            self._retire_router_side(req, "error")

    def _retire_router_side(self, req: Request, reason: str):
        """Finish a request the router could not re-place — explicitly
        retired (never silently lost)."""
        req.state = FINISHED
        req.finish_reason = reason
        req.slot = None
        req.blocks = []
        self._finished[req.request_id] = req

    def revive(self, name: Optional[str] = None):
        """``Engine.revive()`` passthrough: one replica by name, or the
        whole fleet when ``name`` is None."""
        for rep in self.replicas:
            if name is None or rep.name == name:
                rep.engine.revive()

    # ------------------------------------------------------ observation
    def _publish_gauges(self):
        states = [r.engine.overload.health.state for r in self.replicas]
        self.metrics.set_fleet_gauges(
            serving=sum(s == SERVING for s in states),
            total=len(self.replicas),
            queue_depth=sum(len(r.engine.scheduler.waiting)
                            for r in self.replicas),
            pending_tokens=sum(r.engine.pending_prefill_tokens()
                               for r in self.replicas))

    def health(self) -> dict:
        """Aggregate fleet health: worst-of replica states (all FAILED
        → failed; any non-SERVING → degraded) plus per-replica
        snapshots — the shape ``Endpoint.health()`` forwards."""
        per = {r.name: r.engine.health() for r in self.replicas}
        states = [h["state"] for h in per.values()]
        if all(s == FAILED for s in states):
            state = FAILED
        elif any(s != SERVING for s in states):
            state = DEGRADED
        else:
            state = SERVING
        return {
            "state": state,
            "serving_replicas": sum(s == SERVING for s in states),
            "failed_replicas": sum(s == FAILED for s in states),
            "queue_depth": sum(h["queue_depth"] for h in per.values()),
            "pending_prefill_tokens": sum(
                r.engine.pending_prefill_tokens() for r in self.replicas),
            "replicas": per,
        }

    def stats(self) -> dict:
        """Fleet stats: the router's own counters plus every replica's
        ``Engine.stats()`` snapshot and the fleet-wide realized
        cached-token ratio (prompt tokens served from prefix caches)."""
        cached = sum(r.engine.metrics._cached_tokens_sum
                     for r in self.replicas)
        prompts = sum(r.engine.metrics._prompt_tokens_sum
                      for r in self.replicas)
        self._publish_gauges()
        return {
            "router": {
                "policy": self.policy,
                "seed": self.seed,
                "replicas": [r.name for r in self.replicas],
                "cached_token_ratio": round(cached / max(prompts, 1), 4),
                **self.metrics.as_dict(),
            },
            "replicas": {r.name: r.engine.stats()
                         for r in self.replicas},
        }

    def placement_log_text(self) -> str:
        """The placement decisions, one line per request, newline-joined
        — byte-identical across runs for the same trace + seed on a
        fresh fleet (the determinism contract tests pin)."""
        return "\n".join(self.placement_log)


__all__ = ["Router", "RouterMetrics", "ROUTER_POLICIES"]
