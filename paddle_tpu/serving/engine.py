# lint-tpu: disable-file=L004 -- serving drives the compiled decode/
# prefill steps over raw device buffers (like models/); new backend code
# belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Continuous-batching inference engine (PAPERS.md: Orca's
iteration-level scheduling + vLLM's paged KV cache) over the compiled
steps of models/generation.py.

The engine keeps a fixed BUCKET of ``max_batch_size`` decode slots.
Every iteration it (1) retires finished sequences, (2) admits waiting
requests into free slots — one compiled prefill per prompt, bucketed to
block multiples — and (3) runs ONE compiled decode step over the whole
bucket: token ids [S, 1], the shared block pools, block tables
[S, max_blocks] and per-slot frontiers [S].  Because every array shape
is fixed by the config, the decode step compiles exactly once; idle
slots decode into the reserved garbage block instead of branching.
Requests therefore enter and leave at TOKEN granularity — no
batch-completion barrier, which is what turns the static decode step
into a serving engine.

Correctness contract: greedy outputs are token-exact with sequential
``generate()`` for the same prompts (tests/test_serving.py), including
across preemption (recompute-from-prompt is deterministic under
greedy).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional

import contextlib

import jax.numpy as jnp
import numpy as np

from ..models.generation import (_cache_dims, make_paged_decode_step,
                                 make_prefill_step,
                                 normalize_stop_sequences)
from ..observability import track_compiles, warn_on_retrace
from .. import profiler
from .cache import BlockKVPool, PoolExhausted
from .metrics import ServingMetrics
from .scheduler import (FINISHED, RUNNING, AdmissionError, Request,
                        Scheduler)


def _trace(name: str):
    """Profiler range for the serving hot path — a no-op unless a
    profiler session is recording (RecordEvent buffers until drained, so
    unconditional use would grow host memory for the engine's lifetime)."""
    if profiler.current_profiler() is not None:
        return profiler.RecordEvent(name)
    return contextlib.nullcontext()


@dataclass
class ServingConfig:
    """Engine tuning knobs (README "Serving" documents each)."""

    max_batch_size: int = 8       # decode-bucket slots
    block_size: int = 16          # KV-cache tokens per block
    num_blocks: int = 128         # pool size incl. reserved block 0
    max_queue_len: int = 64       # bounded wait queue (backpressure)
    max_model_len: Optional[int] = None   # default: model max positions
    # raise (observability.RetraceError, a RuntimeError) if the compiled
    # decode step ever retraces after warmup — the H101-style jit
    # cache-key check via observability.warn_on_retrace; cheap, keep on.
    # When False, retraces are still counted (engine._decode_step.retraces)
    strict_no_retrace: bool = True


class Engine:
    """Continuous-batching engine for any causal LM following the
    cache contract of models/llama.py (StaticKVCache + PagedKVCache)."""

    def __init__(self, model, config: Optional[ServingConfig] = None):
        self.model = model
        self.config = cfg = config or ServingConfig()
        kv_heads, head_dim, dtype = _cache_dims(model)
        model_max = getattr(model.config, "max_position_embeddings", None)
        self.max_model_len = min(
            cfg.max_model_len or model_max or 1 << 30,
            model_max or 1 << 30)
        self.max_blocks_per_seq = -(-self.max_model_len // cfg.block_size)
        self.pool = BlockKVPool(
            model.config.num_hidden_layers, cfg.num_blocks, cfg.block_size,
            kv_heads, head_dim, dtype)
        self.scheduler = Scheduler(self.pool,
                                   max_queue_len=cfg.max_queue_len)
        self.metrics = ServingMetrics()
        S = cfg.max_batch_size
        self._slots: List[Optional[Request]] = [None] * S
        self._block_tables = np.zeros((S, self.max_blocks_per_seq),
                                      np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._pending = np.zeros((S,), np.int32)  # next token to decode
        # compile accounting wraps both compiled entry points.  The
        # decode step carries the no-retrace contract: its ONE allowed
        # compile is this engine's warmup; any cache growth past it seen
        # through this wrapper is a retrace (the step is cached on the
        # model, so another engine's entries never count against us).
        self._decode_step = warn_on_retrace(
            make_paged_decode_step(model), after=1,
            label="serving::decode_step",
            on_retrace="raise" if cfg.strict_no_retrace else "count")
        # prefill legitimately compiles once per bucketed prompt length
        self._prefill_step = track_compiles(
            make_prefill_step(model), label="serving::prefill_step")
        self._finished: Dict[str, Request] = {}
        self._ids = itertools.count()

    # ----------------------------------------------------------- submit
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None, stop_sequences=None,
               tokenizer=None, request_id: Optional[str] = None,
               temperature: float = 0.0, do_sample: bool = False,
               deadline_s: Optional[float] = None
               ) -> Request:
        """Queue one request; returns its :class:`Request` handle.
        Raises :class:`AdmissionError` when the wait queue is full or
        the sequence can never fit the pool (backpressure: callers
        retry or shed load).

        ``deadline_s`` is a wall-clock SLO measured from submission:
        once exceeded the request is retired with
        ``finish_reason="timeout"`` (partial tokens kept) — whether it
        is still queued or mid-decode — instead of occupying a slot
        other requests could use.

        ``temperature``/``do_sample`` exist for ``generate()`` call-site
        parity only: the engine decodes greedily (one shared compiled
        step for the whole bucket), so greedy settings are accepted and
        a sampling request is a loud :class:`ValueError` rather than a
        silently different decode."""
        if do_sample or (temperature is not None
                         and float(temperature) != 0.0):
            raise ValueError(
                "the serving engine decodes greedily; sampling "
                "(do_sample=True or temperature>0) is not supported — "
                "use temperature=0.0, generate()'s greedy contract")
        prompt = np.asarray(
            prompt.numpy() if hasattr(prompt, "numpy") else prompt,
            np.int32).reshape(-1)
        req = Request(
            prompt=prompt, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id,
            stop_sequences=normalize_stop_sequences(stop_sequences,
                                                    tokenizer),
            request_id=request_id or f"req-{next(self._ids)}",
            deadline_s=deadline_s)
        if req.prompt_len + req.max_new_tokens > self.max_model_len:
            self.metrics.on_reject()
            raise AdmissionError(
                f"{req.request_id}: prompt ({req.prompt_len}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds "
                f"max_model_len ({self.max_model_len})")
        try:
            self.scheduler.enqueue(req)
        except AdmissionError:
            self.metrics.on_reject()
            raise
        self.metrics.on_submit(req.request_id)
        return req

    # ------------------------------------------------------------- step
    def step(self) -> bool:
        """One engine iteration: retire/admit at token granularity, then
        one compiled decode step over the bucket.  Returns True while
        there is work left (running or waiting)."""
        self._admit()
        if any(r is not None for r in self._slots):
            self._decode_iteration()
        return self.has_work()

    def has_work(self) -> bool:
        return bool(self.scheduler.waiting) or \
            any(r is not None for r in self._slots)

    def run_until_complete(self) -> Dict[str, Request]:
        """Drain queue + bucket; returns {request_id: Request} of every
        request finished during this drain."""
        while self.step():
            pass
        done, self._finished = self._finished, {}
        return done

    def generate(self, prompts, **submit_kwargs) -> List[np.ndarray]:
        """Batch convenience mirroring ``generate()``: submit every
        prompt, drain, return outputs (prompt + generated) in order."""
        reqs = [self.submit(p, **submit_kwargs) for p in prompts]
        self.run_until_complete()
        return [r.output_ids() for r in reqs]

    # -------------------------------------------------------- admission
    def _admit(self):
        # deadline sweep over the WAIT queue: an expired request must
        # not consume a prefill + slot it can no longer use
        for req in [r for r in self.scheduler.waiting if r.expired()]:
            self.scheduler.waiting.remove(req)
            self._retire(req, "timeout")
        free_slots = [i for i, r in enumerate(self._slots) if r is None]
        while free_slots:
            req = self.scheduler.next_admittable()
            if req is None:
                break
            self._prefill(req, free_slots.pop(0))

    def _prefill(self, req: Request, slot: int):
        bs = self.config.block_size
        n = self.pool.blocks_for(req.prompt_len)
        blocks = self.pool.allocate(req.request_id, n)
        self.metrics.on_admit(req.request_id)
        try:
            from ..resilience import chaos

            chaos.maybe_fail_request(req.request_id)
            with _trace(f"serving::prefill:{req.request_id}"):
                ids = np.zeros((1, n * bs), np.int32)
                ids[0, :req.prompt_len] = req.prompt
                z = jnp.zeros((1, n * bs, self.pool.kv_heads,
                               self.pool.head_dim), self.pool.dtype)
                caches = [(z, z) for _ in range(self.pool.num_layers)]
                last, caches = self._prefill_step(
                    ids, caches, np.int32(req.prompt_len - 1))
                self.pool.install_prefill(blocks, caches)
            first_tok = int(np.argmax(np.asarray(last)[0]))
        except Exception as e:  # noqa: BLE001 — poison-request isolation
            # ONE malformed request must not kill the engine loop: fail
            # and retire it, free its blocks, keep serving the rest
            req.error = f"{type(e).__name__}: {e}"
            self._retire(req, "error")
            return
        req.state = RUNNING
        req.slot = slot
        req.blocks = blocks
        req.generated = [first_tok]
        self.scheduler.running.append(req)
        self.metrics.on_first_token(req.request_id)
        self._slots[slot] = req
        self._block_tables[slot] = 0
        self._block_tables[slot, :n] = blocks
        self._lengths[slot] = req.prompt_len
        self._pending[slot] = first_tok
        # the prefill's token may already terminate the request
        self._maybe_retire(req)

    # ---------------------------------------------------------- decode
    def _ensure_blocks(self):
        """Every live slot needs a block for its next write position;
        allocate, preempting YOUNGEST-first when the pool is dry —
        oldest first, so a starving old request evicts young ones, never
        the reverse (a young request that cannot get a block preempts
        ITSELF before touching older work)."""
        for req in sorted(self.scheduler.running,
                          key=lambda r: r.ordinal):
            if req.slot is None:        # preempted earlier in this pass
                continue
            need = self.pool.blocks_for(int(self._lengths[req.slot]) + 1)
            while len(req.blocks) < need:
                try:
                    new = self.pool.allocate(req.request_id, 1)
                except PoolExhausted:
                    victim = self.scheduler.pick_victim()
                    if victim is None:
                        # unreachable: enqueue() capacity check
                        # guarantees a sole-running request always fits
                        raise
                    self._preempt(victim)
                    if victim is req:
                        break
                    continue
                self._block_tables[req.slot, len(req.blocks)] = new[0]
                req.blocks.extend(new)

    def _preempt(self, victim: Request):
        """Evict-and-requeue (recompute mode): free everything, head of
        the queue, original FCFS ordinal."""
        slot = victim.slot
        self.scheduler.running.remove(victim)
        self.pool.free_request(victim.request_id)
        victim.preemptions += 1
        self.metrics.on_preempt(victim.request_id)
        self._slots[slot] = None
        self._block_tables[slot] = 0
        self._lengths[slot] = 0
        self._pending[slot] = 0
        self.scheduler.requeue_preempted(victim)

    def _decode_iteration(self):
        self._ensure_blocks()
        active = [r for r in self._slots if r is not None]
        if not active:
            return
        with _trace("serving::decode_step"):
            logits, new_pools = self._decode_step(
                self._pending[:, None], self.pool.layers,
                self._block_tables, self._lengths)
            self.pool.layers = [(k, v) for k, v in new_pools]
            logits = np.asarray(logits)
        self.metrics.on_decode_iteration(
            len(active), self.config.max_batch_size,
            self.pool.utilization())
        for req in active:
            slot = req.slot
            # the pending token was written at position lengths[slot]
            self._lengths[slot] += 1
            next_tok = int(np.argmax(logits[slot]))
            req.generated.append(next_tok)
            self._pending[slot] = next_tok
            self._maybe_retire(req)

    # ----------------------------------------------------------- retire
    def _maybe_retire(self, req: Request):
        reason = self.scheduler.finish_reason(req)
        if reason is not None:
            self._retire(req, reason)

    def _retire(self, req: Request, reason: str):
        """Finish ``req`` for ``reason`` from ANY state — running in a
        slot, or never admitted (queued timeout / failed prefill)."""
        slot = req.slot
        req.state = FINISHED
        req.finish_reason = reason
        if req in self.scheduler.running:
            self.scheduler.running.remove(req)
        self.pool.free_request(req.request_id)
        req.slot = None
        if slot is not None:
            self._slots[slot] = None
            self._block_tables[slot] = 0
            self._lengths[slot] = 0
            self._pending[slot] = 0
        self.metrics.on_finish(req.request_id, req.num_generated, reason)
        self._finished[req.request_id] = req

    # ------------------------------------------------------------ misc
    def decode_cache_size(self) -> int:
        """Entries in the compiled decode step's jit cache — 1 after
        warmup, forever (the no-retrace contract)."""
        return self._decode_step._cache_size()

    def stats(self) -> dict:
        d = self.metrics.as_dict()
        d["pool"] = self.pool.stats()
        d["queue_depth"] = len(self.scheduler.waiting)
        return d
