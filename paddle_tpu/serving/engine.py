# lint-tpu: disable-file=L004 -- serving drives the compiled decode/
# prefill steps over raw device buffers (like models/); new backend code
# belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Continuous-batching inference engine (PAPERS.md: Orca's
iteration-level scheduling + vLLM's paged KV cache + Sarathi-style
chunked prefill) over the compiled steps of models/generation.py.

The engine keeps a fixed BUCKET of ``max_batch_size`` decode slots.
Every iteration it (1) retires finished sequences, (2) admits waiting
requests into free slots — attaching any prefix-cached blocks of the
prompt and allocating only the uncached suffix — (3) advances admitted
prompts by fixed-size prefill CHUNKS under a per-iteration token
budget, and (4) runs ONE compiled decode step over the whole bucket:
token ids [S, 1], the shared block pools, block tables [S, max_blocks]
and per-slot frontiers [S].  Because every array shape is fixed by the
config — including the prefill chunk's — the decode step AND the
prefill step each compile exactly once; idle slots decode into the
reserved garbage block instead of branching, and mid-prefill slots are
masked out of the decode view the same way.  Requests therefore enter
and leave at TOKEN granularity, and a long prompt no longer stalls
running requests for its whole prefill — it yields the iteration back
to decode after each chunk.

Correctness contract: greedy outputs are token-exact with sequential
``generate()`` for the same prompts (tests/test_serving.py), including
across preemption (recompute-from-prompt is deterministic under
greedy), with the prefix cache on or off (shared blocks hold the exact
bits a fresh prefill would produce; copy-on-write keeps them immutable).
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import contextlib

import numpy as np

from ..models.generation import (_cache_dims, make_chunked_prefill_step,
                                 make_paged_decode_step,
                                 normalize_stop_sequences)
from ..observability import warn_on_retrace
from .. import profiler
from .cache import BlockKVPool, PoolExhausted
from .metrics import ServingMetrics
from .overload import EngineQuarantined, OverloadController
from .sampling import make_sampled_decode_step, resolve_sampling, sample_at
from .scheduler import (FINISHED, PREFILLING, RUNNING, AdmissionError,
                        QueueFull, Request, Scheduler)
from .speculative import (SpeculativeConfig, make_draft_propose_step,
                          make_spec_verify_step)


def _trace(name: str):
    """Profiler range for the serving hot path — a no-op unless a
    profiler session is recording (RecordEvent buffers until drained, so
    unconditional use would grow host memory for the engine's lifetime)."""
    if profiler.current_profiler() is not None:
        return profiler.RecordEvent(name)
    return contextlib.nullcontext()


@dataclass
class ServingConfig:
    """Engine tuning knobs (README "Serving" documents each)."""

    # replica name (serving/router.py fleets): suffixes the watchdog /
    # chaos step labels as "serving::decode_step@<name>" so per-replica
    # fault injection and metrics can target ONE engine of a fleet.
    # Empty (the default) keeps the bare single-engine labels.
    name: str = ""
    max_batch_size: int = 8       # decode-bucket slots
    block_size: int = 16          # KV-cache tokens per block
    num_blocks: int = 128         # pool size incl. reserved block 0
    max_queue_len: int = 64       # bounded wait queue (backpressure)
    max_model_len: Optional[int] = None   # default: model max positions
    # prefill chunk size in tokens: every prompt prefills as fixed
    # [1, chunk_tokens] chunks, so prefill holds ONE compiled program
    # for all prompt lengths (clamped to max_model_len)
    chunk_tokens: int = 256
    # content-addressed KV block reuse across requests sharing a prompt
    # prefix (block-granular; LRU eviction of unreferenced blocks)
    enable_prefix_cache: bool = True
    # max prefill tokens computed per engine iteration before decode
    # runs again (Sarathi-style interleave); None = one chunk's worth
    prefill_token_budget: Optional[int] = None
    # raise (observability.RetraceError, a RuntimeError) if the compiled
    # decode step ever retraces after warmup — the H101-style jit
    # cache-key check via observability.warn_on_retrace; cheap, keep on.
    # When False, retraces are still counted (engine._decode_step.retraces)
    strict_no_retrace: bool = True
    # X-ray both compiled steps at startup (analysis.xray): static
    # FLOPs/bytes/peak-HBM land in engine.xray_reports and (when
    # telemetry is on) the observability gauges; ERROR-severity hazards
    # — f64 eqns, host callbacks, or peak HBM over hbm_budget_bytes —
    # raise before the engine serves a single token
    xray_on_start: bool = False
    hbm_budget_bytes: Optional[int] = None   # None: no H110 gate
    xray_chip: str = "v5e"                   # roofline ridge profile
    # static shard-plan audit at construction (analysis.shardplan):
    # an analysis.PlanRequest (or True for the default llama layout on
    # a simulated (data=2, fsdp=2, tp=2) mesh).  Propagates shardings
    # through the decode + chunked-prefill programs, mirrors per-chip
    # peak HBM and collective bytes into the observability gauges, and
    # aborts construction on S205/S207/H110-per-chip ERRORs — all on
    # CPU, no devices needed.
    shardplan: Any = None
    # RUNTIME mesh execution (distributed.MeshExecutor, or an
    # {axis: size} dict): weights are sharded per the canonical
    # SpecLayout and the paged KV pool PS(None, None, "tp", None), so
    # decode/prefill each run as ONE GSPMD program over the mesh.
    # Engine.reconcile_mesh() audits the compiled programs against the
    # static shard plan (diagnostic S209).
    mesh: Any = None
    # ---- overload control (serving/overload.py; README "Overload
    # control & graceful degradation") ----
    # deadline-aware load shedding at submit(): reject with
    # finish_reason="shed" when the estimated TTFT (queue depth +
    # pending prefill tokens over the chunk/decode latency EWMAs)
    # already busts deadline_s.  Never fires while the EWMAs are cold.
    enable_load_shedding: bool = True
    shed_safety_factor: float = 1.0   # shed when est > deadline * factor
    # KV memory-pressure watermarks (fraction of pool blocks referenced
    # by live requests) driving the degradation ladder, with hysteresis:
    # escalate one level per iteration STRICTLY above high, unwind one
    # below low.  The default high of 1.0 cannot be exceeded, so the
    # ladder is opt-in: set e.g. 0.9/0.7 to start degrading before the
    # pool is fully referenced (preemption still guards the full-pool
    # case either way)
    kv_high_watermark: float = 1.0
    kv_low_watermark: float = 0.75
    # hung-step watchdog: per-attempt budget = watchdog_budget_mult x
    # the step's EWMA latency, floored by watchdog_floor_s (generous:
    # the first call pays XLA compilation); a stall or transient step
    # exception gets step_max_retries retries with exponential backoff
    # from step_retry_backoff_s, then the engine quarantines DEGRADED
    # (stalls) or FAILED (exceptions, raising EngineQuarantined)
    watchdog_budget_mult: float = 20.0
    watchdog_floor_s: float = 30.0
    step_max_retries: int = 2
    step_retry_backoff_s: float = 0.05
    # consecutive in-budget steps before DEGRADED self-heals to SERVING
    health_recovery_steps: int = 3
    # fused serving kernels (kernels/fusion): None resolves the
    # FLAGS_use_fused_serving default (fused on TPU, unfused elsewhere);
    # True forces the fused paged-attention decode + RMSNorm epilogues
    # even on CPU (the XLA fallback — how CI covers the fused math);
    # False pins the unfused reference path on any backend.  Pinned at
    # step-build time, so it never flips inside a compiled program.
    fused_kernels: Optional[bool] = None
    # speculative decoding (serving/speculative.py): a SpeculativeConfig
    # (or a bare draft model, wrapped with the default K).  The draft's
    # KV layers live in the SAME BlockKVPool as the target's — one
    # block table per sequence, so the prefix cache serves both models
    # — and every decode iteration becomes draft-propose (K tokens, one
    # scanned program) + target-verify ([S, K+1], one chunked-shaped
    # program) with on-device acceptance and block-granular KV rollback.
    speculative: Any = None
    # ---- quantized serving (ISSUE 20; kernels/kv_quant) ----
    # KV-cache storage dtype: None serves full precision; "int8"/"fp8"
    # store the paged pools as int8 codes + per-(block, token)-row f32
    # absmax scales, quantizing at KV-write time inside the traced
    # steps and dequantizing at the attention kernels' DMA boundary.
    # The prefix-cache hash chain is namespaced by this dtype, so a
    # quantized pool never matches fp32-registered blocks.
    kv_cache_dtype: Optional[str] = None
    # weight-only quantization: "int8" converts every Column/Row-
    # parallel linear to absmax per-out-channel int8 codes dequantized
    # in the matmul prologue (paddle_tpu/quantization/serving.py) —
    # the paddle Int8Linear inference analog.  Applied IN PLACE to the
    # model at engine construction, before the steps trace.
    weight_dtype: Optional[str] = None
    # fixed KV HBM budget: when set, ``num_blocks`` is DERIVED as
    # kv_pool_bytes // pool-block-bytes (dtype-aware, scale sidecars
    # included).  The like-for-like capacity knob behind the int8-vs-
    # fp32 occupancy/goodput comparison: same bytes, ~4x the blocks at
    # int8, so the degradation ladder engages later under the same
    # burst.
    kv_pool_bytes: Optional[int] = None


class Engine:
    """Continuous-batching engine for any causal LM following the
    cache contract of models/llama.py (StaticKVCache + PagedKVCache)."""

    def __init__(self, model, config: Optional[ServingConfig] = None):
        from ..kernels.kv_quant import resolve_kv_cache_dtype

        self.model = model
        self.config = cfg = config or ServingConfig()
        self.kv_cache_dtype = resolve_kv_cache_dtype(cfg.kv_cache_dtype)
        if cfg.weight_dtype:
            # in place, idempotent, BEFORE the steps trace (they capture
            # the weights as jit constants)
            from ..quantization.serving import quantize_model_weights

            quantize_model_weights(model, cfg.weight_dtype)
        kv_heads, head_dim, dtype = _cache_dims(model)
        model_max = getattr(model.config, "max_position_embeddings", None)
        self.max_model_len = min(
            cfg.max_model_len or model_max or 1 << 30,
            model_max or 1 << 30)
        self.max_blocks_per_seq = -(-self.max_model_len // cfg.block_size)
        self.chunk_tokens = max(1, min(cfg.chunk_tokens,
                                       self.max_model_len))
        # speculative decoding: one pool holds the target's layers
        # followed by the draft's, addressed by the same block tables
        spec = cfg.speculative
        if spec is not None and not isinstance(spec, SpeculativeConfig):
            spec = SpeculativeConfig(draft_model=spec)
        self.spec = spec
        self._n_target_layers = model.config.num_hidden_layers
        num_layers = self._n_target_layers
        if spec is not None:
            spec.validate_against(model)
            if cfg.mesh is not None:
                raise ValueError(
                    "speculative decoding under a runtime mesh is not "
                    "supported yet (the draft's weights would stay "
                    "unsharded)")
            draft_max = getattr(spec.draft_model.config,
                                "max_position_embeddings", None)
            if draft_max is not None and draft_max < self.max_model_len:
                raise ValueError(
                    f"draft max_position_embeddings ({draft_max}) < "
                    f"max_model_len ({self.max_model_len})")
            num_layers += spec.draft_model.config.num_hidden_layers
        if spec is not None and self.kv_cache_dtype is not None:
            raise ValueError(
                "speculative decoding with a quantized KV cache is not "
                "supported yet (the draft/verify rollback paths assume "
                "full-precision pool entries); drop kv_cache_dtype or "
                "speculative")
        # fixed-HBM sizing: a kv_pool_bytes budget derives num_blocks
        # from the per-dtype block bytes (quantized pools fit ~4x the
        # blocks in the same budget — the occupancy headline)
        self.num_blocks = cfg.num_blocks
        if cfg.kv_pool_bytes is not None:
            per_block = BlockKVPool.block_bytes_for(
                num_layers, cfg.block_size, kv_heads, head_dim, dtype,
                self.kv_cache_dtype)
            self.num_blocks = int(cfg.kv_pool_bytes) // per_block
            if self.num_blocks < 2:
                raise ValueError(
                    f"kv_pool_bytes={cfg.kv_pool_bytes} fits only "
                    f"{self.num_blocks} block(s) of {per_block} bytes; "
                    "need >= 2 (block 0 is the reserved garbage sink)")
        self.pool = BlockKVPool(
            num_layers, self.num_blocks, cfg.block_size,
            kv_heads, head_dim, dtype,
            enable_prefix_cache=cfg.enable_prefix_cache,
            kv_cache_dtype=self.kv_cache_dtype)
        self.scheduler = Scheduler(self.pool,
                                   max_queue_len=cfg.max_queue_len)
        self.metrics = ServingMetrics()
        from ..kernels.kv_quant import (kv_pool_dtype_code,
                                        kv_scale_bytes_per_block)

        self.metrics.on_kv_cache_config(
            kv_pool_dtype_code(self.kv_cache_dtype),
            kv_scale_bytes_per_block(cfg.block_size, self.kv_cache_dtype))
        self.overload = OverloadController(cfg, self.metrics)
        S = cfg.max_batch_size
        self._slots: List[Optional[Request]] = [None] * S
        self._block_tables = np.zeros((S, self.max_blocks_per_seq),
                                      np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._pending = np.zeros((S,), np.int32)  # next token to decode
        # per-slot sampling state, all fixed-shape device-step inputs:
        # greedy slots keep temperature 0 (the argmax lane inside the
        # sampled/verify steps) so a mixed bucket is still ONE program
        self._temps = np.zeros((S,), np.float32)
        self._top_ks = np.zeros((S,), np.int32)
        self._top_ps = np.ones((S,), np.float32)
        self._keys = np.zeros((S, 2), np.uint32)      # per-request base keys
        self._counters = np.zeros((S,), np.int32)     # next token index
        # runtime SPMD: shard weights + KV pool BEFORE the step makers
        # below — the steps capture the weights as jit constants, so the
        # rebind here is what makes the compiled programs multi-device
        self.mesh_executor = None
        if cfg.mesh is not None:
            from ..distributed.executor import as_executor

            self.mesh_executor = as_executor(cfg.mesh)
            self.mesh_executor.install_serving(model, self.pool)
        # compile accounting wraps both compiled entry points, and BOTH
        # carry the no-retrace contract now: each one's single allowed
        # compile is this engine's warmup; any cache growth past it seen
        # through these wrappers is a retrace (the steps are cached on
        # the model, so another engine's entries never count against us).
        # Chunked prefill earns its wrapper by construction — one fixed
        # [1, chunk_tokens] shape for EVERY prompt length, where the old
        # bucketed prefill compiled one program per length bucket.
        self._decode_step = warn_on_retrace(
            make_paged_decode_step(model, fused=cfg.fused_kernels,
                                   kv_cache_dtype=self.kv_cache_dtype),
            after=1, label="serving::decode_step",
            on_retrace="raise" if cfg.strict_no_retrace else "count")
        self._prefill_step = warn_on_retrace(
            make_chunked_prefill_step(model, fused=cfg.fused_kernels,
                                      kv_cache_dtype=self.kv_cache_dtype),
            after=1, label="serving::prefill_step",
            on_retrace="raise" if cfg.strict_no_retrace else "count")
        self._sampled_decode_step = warn_on_retrace(
            make_sampled_decode_step(model, fused=cfg.fused_kernels,
                                     kv_cache_dtype=self.kv_cache_dtype),
            after=1, label="serving::sampled_decode_step",
            on_retrace="raise" if cfg.strict_no_retrace else "count")
        # every ADDITIONAL compiled step gets its own watchdog: the
        # per-EWMA compile_s carve-out only exempts ONE first call, so
        # sharing the decode/prefill watchdogs would record the second
        # program's compile as a real latency sample and poison the
        # budget + TTFT estimate (over-shedding) for good
        self._sampled_wd = self.overload.extra_watchdog(
            "sampled_decode_step")
        if spec is not None:
            draft = spec.draft_model
            self._draft_prefill_step = warn_on_retrace(
                make_chunked_prefill_step(draft, fused=cfg.fused_kernels),
                after=1, label="serving::draft_prefill_step",
                on_retrace="raise" if cfg.strict_no_retrace else "count")
            self._draft_propose_step = warn_on_retrace(
                make_draft_propose_step(draft, spec.num_draft_tokens,
                                        fused=cfg.fused_kernels),
                after=1, label="serving::draft_propose_step",
                on_retrace="raise" if cfg.strict_no_retrace else "count")
            self._spec_verify_step = warn_on_retrace(
                make_spec_verify_step(model, spec.num_draft_tokens,
                                      fused=cfg.fused_kernels),
                after=1, label="serving::spec_verify_step",
                on_retrace="raise" if cfg.strict_no_retrace else "count")
            self._draft_prefill_wd = self.overload.extra_watchdog(
                "draft_prefill_step")
            self._draft_propose_wd = self.overload.extra_watchdog(
                "draft_propose_step")
            self._spec_verify_wd = self.overload.extra_watchdog(
                "spec_verify_step")
        self._finished: Dict[str, Request] = {}
        self._ids = itertools.count()
        self._evictions_seen = 0    # pool counter already mirrored
        self.xray_reports = self._xray_startup() if cfg.xray_on_start \
            else None
        self.shardplan_reports = self._shardplan_startup() \
            if cfg.shardplan is not None else None

    def _shardplan_startup(self):
        """Statically plan the decode and chunked-prefill programs on
        this engine's exact shapes against an abstract mesh
        (analysis.shardplan) before serving: per-chip peak HBM and the
        collective inventory mirror into the observability gauges, and
        ERRORs — S205 resharding, S207 collective-bound, H110 per-chip
        budget — abort construction."""
        from ..analysis import PlanRequest, shardplan, xray

        cfg = self.config
        req = cfg.shardplan
        if req is True:
            req = PlanRequest(hbm_budget_bytes=cfg.hbm_budget_bytes)
        layout = req.resolved_layout()
        decode_args, prefill_args = xray._serving_abstract_args(
            self.model, batch=cfg.max_batch_size,
            num_blocks=self.num_blocks, block_size=cfg.block_size,
            max_blocks_per_seq=self.max_blocks_per_seq,
            chunk_tokens=self.chunk_tokens,
            kv_cache_dtype=self.kv_cache_dtype)
        decode_specs, prefill_specs = shardplan._serving_arg_specs(
            self.model, layout, decode_args, prefill_args)
        reports = [
            shardplan.plan_step(
                self._decode_step, decode_args, model=self.model,
                arg_specs=decode_specs, request=req,
                name="serving::decode_step",
                data_input_leaves=(("tokens", 0),),
                step_kind="paged_decode"),
            shardplan.plan_step(
                self._prefill_step, prefill_args, model=self.model,
                arg_specs=prefill_specs, request=req,
                name="serving::prefill_step",
                data_input_leaves=(("chunk_ids", 0),),
                step_kind="chunked_prefill"),
        ]
        errors = [d for r in reports for d in r.errors()]
        for r in reports:
            shardplan.export_plan_gauges(r)
        if errors and getattr(req, "raise_on_error", True):
            raise ValueError(
                "serving step shard plan found ERRORs:\n  " +
                "\n  ".join(str(d) for d in errors))
        return reports

    def reconcile_mesh(self):
        """Cross-check the COMPILED decode/prefill programs against the
        static shard plan (diagnostic S209: collective footprint,
        per-device memory, realized KV-pool output shards).  Returns
        ``{step_name: (PlanReport, [S209 diagnostics])}`` — empty
        diagnostic lists mean runtime and plan agree."""
        if self.mesh_executor is None:
            raise RuntimeError(
                "reconcile_mesh needs ServingConfig(mesh=...)")
        return self.mesh_executor.reconcile_serving(self)

    def _xray_startup(self):
        """X-ray the decode and prefill steps on this engine's exact
        shapes (analysis.xray) before serving: static FLOPs/bytes/peak-
        HBM mirror into the observability gauges, and ERROR hazards —
        f64, host callbacks, HBM budget (H110) — abort construction."""
        from ..analysis import xray

        cfg = self.config
        decode_args, prefill_args = xray._serving_abstract_args(
            self.model, batch=cfg.max_batch_size,
            num_blocks=self.num_blocks, block_size=cfg.block_size,
            max_blocks_per_seq=self.max_blocks_per_seq,
            chunk_tokens=self.chunk_tokens,
            kv_cache_dtype=self.kv_cache_dtype)
        reports = [
            xray.analyze(self._decode_step, decode_args,
                         name="serving::decode_step", chip=cfg.xray_chip,
                         hbm_budget_bytes=cfg.hbm_budget_bytes),
            xray.analyze(self._prefill_step, prefill_args,
                         name="serving::prefill_step", chip=cfg.xray_chip,
                         hbm_budget_bytes=cfg.hbm_budget_bytes),
        ]
        errors = [d for r in reports for d in r.errors()]
        for r in reports:
            xray.export_report_gauges(r)
        if errors:
            raise ValueError(
                "serving step X-ray found ERROR hazards:\n  " +
                "\n  ".join(str(d) for d in errors))
        return reports

    # ------------------------------------------------- pool layer slices
    # the combined pool lists the target's layers first, then the
    # draft's; every step consumes only its model's slice, and each
    # rebind reassembles the full list (non-speculative engines pass
    # through untouched)
    def _target_pools(self):
        if self.spec is None:
            return self.pool.layers
        return self.pool.layers[:self._n_target_layers]

    def _draft_pools(self):
        return self.pool.layers[self._n_target_layers:]

    def _rebind_target(self, new_pools):
        # entries are (k, v) or (k, v, k_scale, v_scale) — arity-agnostic
        new = [tuple(entry) for entry in new_pools]
        if self.spec is None:
            self.pool.layers = new
        else:
            self.pool.layers = new + self._draft_pools()

    def _rebind_draft(self, new_pools):
        self.pool.layers = self.pool.layers[:self._n_target_layers] \
            + [tuple(entry) for entry in new_pools]

    # ----------------------------------------------------------- submit
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None, stop_sequences=None,
               tokenizer=None, request_id: Optional[str] = None,
               temperature: float = 0.0, do_sample: bool = False,
               top_k: int = 0, top_p: float = 1.0,
               seed: Optional[int] = None, sampling=None,
               on_token=None, token_deadline_s: Optional[float] = None,
               deadline_s: Optional[float] = None, priority: int = 0
               ) -> Request:
        """Queue one request; returns its :class:`Request` handle.
        Raises :class:`AdmissionError` when the wait queue is full or
        the sequence can never fit the pool (backpressure: callers
        retry or shed load).

        ``deadline_s`` is a monotonic-clock SLO measured from
        submission (``time.monotonic``, so wall-clock steps/NTP slews
        never fire it — hazard H111): once exceeded the request is
        retired with ``finish_reason="timeout"`` (partial tokens kept)
        — whether it is still queued, mid-prefill, or mid-decode —
        instead of occupying a slot other requests could use.  When
        load shedding is enabled and the engine's latency EWMAs are
        warm, a request whose ESTIMATED time-to-first-token already
        busts the deadline is retired immediately with
        ``finish_reason="shed"`` (returned, not raised — cheap
        rejection beats a guaranteed timeout).

        ``priority`` (higher wins) orders overload decisions: admission
        prefers high, shedding and preemption take the lowest first.  A
        higher-priority arrival hitting a FULL queue sheds the
        lowest-priority waiting request instead of being rejected.

        Sampling: ``sampling=SamplingParams(...)`` (or a dict of its
        fields), or the ``generate()``-style spelling —
        ``temperature``/``do_sample``/``top_k``/``top_p``/``seed``.
        ``temperature=0`` stays the greedy special case and runs the
        unchanged greedy decode step; a sampled request carries a
        per-request PRNG key derived from its seed, folded with the
        token index ON DEVICE, so outputs are token-exact with
        ``generate()`` under the same seed regardless of batching or
        preemption (serving/sampling.py).

        Streaming: ``on_token`` fires once per ACCEPTED token (several
        per iteration under speculative decoding), in commit order.
        ``token_deadline_s`` is a rolling inter-token SLO: it resets on
        every emitted token and retires a stalled stream with
        ``finish_reason="timeout"``; the load shedder treats it as an
        effective TTFT bound."""
        if self.overload.health.failed:
            self.metrics.on_reject()
            raise AdmissionError(
                "engine quarantined FAILED "
                f"({self.overload.health.last_error}); revive() after "
                "operator intervention")
        params = resolve_sampling(sampling, temperature=temperature,
                                  do_sample=do_sample, top_k=top_k,
                                  top_p=top_p, seed=seed)
        prompt = np.asarray(
            prompt.numpy() if hasattr(prompt, "numpy") else prompt,
            np.int32).reshape(-1)
        req = Request(
            prompt=prompt, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id,
            stop_sequences=normalize_stop_sequences(stop_sequences,
                                                    tokenizer),
            request_id=request_id or f"req-{next(self._ids)}",
            deadline_s=deadline_s, priority=priority,
            sampling=params,
            sampling_key=params.base_key() if params is not None else None,
            on_token=on_token, token_deadline_s=token_deadline_s)
        # speculation writes K draft positions past the frontier each
        # iteration; the admission bound keeps even the deepest
        # (immediately rolled back) write inside max_model_len
        limit = self.max_model_len - (
            self.spec.num_draft_tokens if self.spec is not None else 0)
        if req.prompt_len + req.max_new_tokens > limit:
            self.metrics.on_reject()
            raise AdmissionError(
                f"{req.request_id}: prompt ({req.prompt_len}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds "
                f"max_model_len ({limit})")
        # deadline-aware load shedding (serving/overload.py): when even
        # an optimistic TTFT estimate busts the SLO, retire now — the
        # caller gets the handle back with finish_reason="shed"
        effective_deadline = deadline_s
        if token_deadline_s is not None:
            effective_deadline = token_deadline_s \
                if effective_deadline is None \
                else min(effective_deadline, token_deadline_s)
        if self.overload.should_shed(self, req.prompt, effective_deadline):
            self.metrics.on_submit(req.request_id)
            if req.on_token is not None:
                self.metrics.on_stream_start()
            self._retire(req, "shed")
            return req
        try:
            self.scheduler.enqueue(req)
        except QueueFull:
            victim = self.scheduler.shed_candidate(req.priority)
            if victim is None:
                self.metrics.on_reject()
                raise
            # full queue, higher-priority arrival: displace the lowest-
            # priority waiting request (shed) and take its place
            self.scheduler.waiting.remove(victim)
            self._retire(victim, "shed")
            self.scheduler.enqueue(req)
        except AdmissionError:
            self.metrics.on_reject()
            raise
        self.metrics.on_submit(req.request_id)
        if req.on_token is not None:
            self.metrics.on_stream_start()
        return req

    # ------------------------------------------------------------- step
    def step(self) -> bool:
        """One engine iteration: retire/admit at token granularity,
        advance admitted prompts by prefill chunks under the token
        budget, then one compiled decode step over the bucket.  Returns
        True while there is work left (running, prefilling or waiting).

        Raises :class:`EngineQuarantined` when the engine is FAILED
        (step watchdog exhausted its retries on exceptions) — call
        :meth:`revive` after operator intervention."""
        if self.overload.health.failed:
            raise EngineQuarantined(
                f"engine quarantined FAILED "
                f"({self.overload.health.last_error}); revive() first")
        # one hysteresis step of the memory-pressure ladder BEFORE
        # admission, so pause_admissions takes effect this iteration
        self.overload.ladder.tick(self)
        self._admit()
        self._prefill_tick()
        if any(r is not None and r.state == RUNNING for r in self._slots):
            self._decode_iteration()
        self._sync_pool_metrics()
        return self.has_work()

    def has_work(self) -> bool:
        return bool(self.scheduler.waiting) or \
            any(r is not None for r in self._slots)

    def run_until_complete(self) -> Dict[str, Request]:
        """Drain queue + bucket; returns {request_id: Request} of every
        request finished during this drain."""
        while self.step():
            pass
        done, self._finished = self._finished, {}
        return done

    def generate(self, prompts, **submit_kwargs) -> List[np.ndarray]:
        """Batch convenience mirroring ``generate()``: submit every
        prompt, drain, return outputs (prompt + generated) in order."""
        reqs = [self.submit(p, **submit_kwargs) for p in prompts]
        self.run_until_complete()
        return [r.output_ids() for r in reqs]

    # -------------------------------------------------------- admission
    def _admit(self):
        # deadline sweep over the WAIT queue: an expired request must
        # not consume a prefill + slot it can no longer use
        for req in [r for r in self.scheduler.waiting if r.expired()]:
            self.scheduler.waiting.remove(req)
            self._retire(req, "timeout")
        if self.overload.ladder.admissions_paused:
            return
        free_slots = [i for i, r in enumerate(self._slots) if r is None]
        while free_slots:
            req = self.scheduler.next_admittable()
            if req is None:
                break
            if not self._begin_prefill(req, free_slots[0]):
                break
            free_slots.pop(0)

    def _begin_prefill(self, req: Request, slot: int) -> bool:
        """Admit ``req`` into ``slot``: attach prefix-cached blocks of
        its prompt (refcount bump, zero compute), allocate blocks for
        the uncached suffix, and mark it PREFILLING — chunks run in
        ``_prefill_tick``.  At least the prompt's LAST token is always
        recomputed, cached or not: its logits row is the first generated
        token, which cached k/v alone cannot produce."""
        matched, need, _ = self.pool.admission_plan(req.prompt,
                                                    extra_tokens=0)
        bs = self.config.block_size
        cached_len = min(len(matched) * bs, req.prompt_len - 1)
        matched = matched[:self.pool.blocks_for(cached_len)] \
            if cached_len else []
        self.pool.acquire(req.request_id, matched)
        n = self.pool.blocks_for(req.prompt_len)
        try:
            suffix = self.pool.allocate(req.request_id, n - len(matched))
        except PoolExhausted:
            # defensive (admission_plan just said yes): hand the blocks
            # back and put the request at the head of the queue
            self.pool.free_request(req.request_id)
            self.scheduler.requeue_preempted(req)
            return False
        blocks = matched + suffix
        req.state = PREFILLING
        req.slot = slot
        req.blocks = blocks
        req.prefill_pos = cached_len
        req.cached_tokens = cached_len
        req.prefill_chunks = 0
        self.scheduler.running.append(req)
        self._slots[slot] = req
        self._block_tables[slot] = 0
        self._block_tables[slot, :len(blocks)] = blocks
        # frontier/pending stay 0 until the prompt completes: the decode
        # view masks this slot's block table to the garbage block
        self._lengths[slot] = 0
        self._pending[slot] = 0
        self.metrics.on_admit(req.request_id)
        self.metrics.on_prefix_lookup(req.request_id, cached_len,
                                      req.prompt_len)
        return True

    def _prefill_tick(self):
        """Advance PREFILLING requests by fixed-shape chunks, oldest
        first, until the per-iteration token budget runs out (at least
        one chunk always runs so prefill can never stall).  A request
        whose final chunk completes gets its first token here and joins
        the decode bucket this same iteration."""
        budget = self.overload.ladder.effective_prefill_budget(
            self.config.prefill_token_budget or self.chunk_tokens)
        prefilling = sorted(
            (r for r in self.scheduler.running if r.state == PREFILLING),
            key=lambda r: r.ordinal)
        for req in prefilling:
            if budget <= 0:
                break
            while budget > 0 and req.state == PREFILLING:
                if req.expired():
                    self._retire(req, "timeout")
                    break
                try:
                    from ..resilience import chaos

                    chaos.maybe_fail_request(req.request_id)
                    with _trace(f"serving::prefill:{req.request_id}"):
                        self._prefill_chunk(req)
                except EngineQuarantined:
                    # an ENGINE-level failure (step watchdog out of
                    # retries) is not the request's fault — propagate
                    # instead of retiring it as poison
                    raise
                except Exception as e:  # noqa: BLE001 — poison isolation
                    # ONE malformed request must not kill the engine
                    # loop: fail and retire it, free its blocks, keep
                    # serving the rest
                    req.error = f"{type(e).__name__}: {e}"
                    self._retire(req, "error")
                    break
                budget -= self.chunk_tokens

    def _prefill_chunk(self, req: Request):
        """Run ONE [1, chunk_tokens] compiled prefill chunk for ``req``
        at its current prompt position, copy-on-write-protecting every
        block the chunk writes into."""
        bs = self.config.block_size
        C = self.chunk_tokens
        start = req.prefill_pos
        n_tok = min(C, req.prompt_len - start)
        # blocks this chunk writes: CoW any that are shared/registered
        # (a cache hit whose last block the final recompute token lands
        # in, or blocks registered by a previous admission)
        for bi in range(start // bs,
                        self.pool.blocks_for(start + n_tok)):
            new = self.pool.ensure_writable(req.request_id,
                                            req.blocks[bi])
            if new != req.blocks[bi]:
                req.blocks[bi] = new
                self._block_tables[req.slot, bi] = new
        ids = np.zeros((1, C), np.int32)
        ids[0, :n_tok] = req.prompt[start:start + n_tok]
        bt = self._block_tables[req.slot:req.slot + 1]
        # watchdog-wrapped dispatch (serving/overload.py): monotonic
        # budget + bounded retry; the compiled step is pure, so a retry
        # recomputes the identical chunk from the unchanged pool.  The
        # pool rebind below happens only after a successful attempt.
        last, new_pools = self.overload.prefill_watchdog.call(
            self._prefill_step, ids, self._target_pools(), bt,
            np.asarray([start], np.int32), np.int32(n_tok - 1))
        self._rebind_target(new_pools)
        if self.spec is not None:
            # the draft prefills the same chunk into its own layer slice
            # of the SAME blocks (already CoW-protected above), so the
            # prefix cache serves both models from one block table
            _, new_draft = self._draft_prefill_wd.call(
                self._draft_prefill_step, ids, self._draft_pools(), bt,
                np.asarray([start], np.int32), np.int32(n_tok - 1))
            self._rebind_draft(new_draft)
        req.prefill_pos = start + n_tok
        req.prefill_chunks += 1
        if req.prefill_pos < req.prompt_len:
            return
        # prompt complete: the last chunk's logits row IS the first token
        # (token index 0 — sampled lanes fold the base key with 0, the
        # same program generate() runs, so the streams agree from the
        # very first token)
        params = req.sampling
        if params is not None:
            first_tok = int(np.asarray(sample_at(
                np.asarray(last).astype(np.float32),
                np.asarray([params.temperature], np.float32),
                np.asarray([params.top_k], np.int32),
                np.asarray([params.top_p], np.float32),
                req.sampling_key[None, :],
                np.asarray([0], np.int32)))[0])
        else:
            first_tok = int(np.argmax(np.asarray(last)[0]))
        req.state = RUNNING
        req.generated = [first_tok]
        slot = req.slot
        self._lengths[slot] = req.prompt_len
        self._pending[slot] = first_tok
        if params is not None:
            self._temps[slot] = params.temperature
            self._top_ks[slot] = params.top_k
            self._top_ps[slot] = params.top_p
            self._keys[slot] = req.sampling_key
        self._counters[slot] = 1
        self.metrics.on_first_token(req.request_id)
        self.metrics.on_prefill_complete(req.request_id,
                                         req.prefill_chunks)
        # publish the prompt's full blocks for future prefix hits (they
        # become immutable; the decode frontier CoWs out as needed)
        self.pool.register_prefix(req.request_id, req.prompt, req.blocks)
        if not self._emit_token(req, first_tok):
            self._retire(req, "error")
            return
        # the prefill's token may already terminate the request
        self._maybe_retire(req)

    # ---------------------------------------------------------- decode
    def _ensure_blocks(self, horizon: int = 1):
        """Every RUNNING slot needs WRITABLE blocks for its next
        ``horizon`` write positions (1 for plain decode; K+1 under
        speculative decoding, where the verify step writes the pending
        token plus K draft positions): allocate when the frontier
        crosses into a new block, copy-on-write when a written block is
        one the prefix cache shares.  Allocation preempts
        YOUNGEST-first when the pool is dry — oldest first, so a
        starving old request evicts young ones, never the reverse (a
        young request that cannot get a block preempts ITSELF before
        touching older work)."""
        for req in sorted(self.scheduler.running,
                          key=lambda r: r.ordinal):
            if req.slot is None or req.state != RUNNING:
                continue
            pos = int(self._lengths[req.slot])
            need = self.pool.blocks_for(pos + horizon)
            preempted = False
            while len(req.blocks) < need:
                try:
                    new = self.pool.allocate(req.request_id, 1)
                except PoolExhausted:
                    victim = self.scheduler.pick_victim()
                    if victim is None:
                        # unreachable: enqueue() capacity check
                        # guarantees a sole-running request always fits
                        raise
                    self._preempt(victim)
                    if victim is req:
                        preempted = True
                        break
                    continue
                self._block_tables[req.slot, len(req.blocks)] = new[0]
                req.blocks.extend(new)
            if preempted:
                continue
            # a written block may be shared (prefix-cache hit on the
            # whole prompt, or a registered prompt tail): break the
            # share before decode writes into it.  Freshly allocated
            # blocks are singly-owned, so ensure_writable is a no-op
            # past the frontier block.
            for fi in range(pos // self.config.block_size, need):
                while True:
                    try:
                        new = self.pool.ensure_writable(req.request_id,
                                                        req.blocks[fi])
                    except PoolExhausted:
                        victim = self.scheduler.pick_victim()
                        if victim is None:
                            raise
                        self._preempt(victim)
                        if victim is req:
                            preempted = True
                            break
                        continue
                    break
                if preempted:
                    break
                if new != req.blocks[fi]:
                    req.blocks[fi] = new
                    self._block_tables[req.slot, fi] = new
            if preempted:
                continue

    def _preempt(self, victim: Request):
        """Evict-and-requeue (recompute mode): free everything, head of
        the queue, original FCFS ordinal."""
        slot = victim.slot
        self.scheduler.running.remove(victim)
        self.pool.free_request(victim.request_id)
        victim.preemptions += 1
        self.metrics.on_preempt(victim.request_id)
        self._slots[slot] = None
        self._block_tables[slot] = 0
        self._lengths[slot] = 0
        self._pending[slot] = 0
        self._clear_sampling_slot(slot)
        self.scheduler.requeue_preempted(victim)

    def _clear_sampling_slot(self, slot: int):
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._top_ps[slot] = 1.0
        self._keys[slot] = 0
        self._counters[slot] = 0

    def _decode_block_view(self):
        """Decode view of the block tables: slots still mid-prefill are
        masked to the garbage block so a bucket-wide step can never
        write into (possibly shared) blocks of an unfinished prompt."""
        bt = self._block_tables
        if any(r is not None and r.state == PREFILLING
               for r in self._slots):
            bt = bt.copy()
            for i, r in enumerate(self._slots):
                if r is not None and r.state == PREFILLING:
                    bt[i] = 0
        return bt

    def _emit_token(self, req: Request, tok: int) -> bool:
        """Per-accepted-token hooks: reset the rolling inter-token
        deadline and fire the streaming callback.  Returns False when
        the callback raised — the CONSUMER failed, so the caller
        retires the request as an error instead of crashing the engine
        loop (poison isolation, same policy as prefill)."""
        if req.token_deadline_s is not None:
            req.token_deadline_t = time.monotonic() + req.token_deadline_s
        if req.on_token is None:
            return True
        try:
            req.on_token(tok)
        except Exception as e:  # noqa: BLE001 — consumer isolation
            req.error = f"on_token callback: {type(e).__name__}: {e}"
            return False
        return True

    def _decode_iteration(self):
        if self.spec is not None:
            self._spec_iteration()
            return
        self._ensure_blocks()
        active = [r for r in self._slots
                  if r is not None and r.state == RUNNING]
        if not active:
            return
        bt = self._decode_block_view()
        if any(r.sampling is not None for r in active):
            self._sampled_iteration(active, bt)
            return
        with _trace("serving::decode_step"):
            # the np.asarray device→host sync happens INSIDE the timed
            # closure so the watchdog budget covers device execution,
            # not just dispatch; retries recompute the same pure step
            # on the unchanged pool (the rebind below is post-success)
            def _timed_decode(tokens, layers, tables, lengths):
                out, pools = self._decode_step(tokens, layers, tables,
                                               lengths)
                return np.asarray(out), pools

            logits, new_pools = self.overload.decode_watchdog.call(
                _timed_decode, self._pending[:, None],
                self._target_pools(), bt, self._lengths)
            self._rebind_target(new_pools)
        self.metrics.on_decode_iteration(
            len(active), self.config.max_batch_size,
            self.pool.utilization())
        for req in active:
            slot = req.slot
            # the pending token was written at position lengths[slot]
            self._lengths[slot] += 1
            next_tok = int(np.argmax(logits[slot]))
            req.generated.append(next_tok)
            self._pending[slot] = next_tok
            self._counters[slot] = len(req.generated)
            if not self._emit_token(req, next_tok):
                self._retire(req, "error")
                continue
            self._maybe_retire(req)

    def _sampled_iteration(self, active, bt):
        """One bucket-wide sampled decode step: identical forward pass
        to the greedy step plus the on-device fold + filter +
        categorical — runs whenever ANY active slot samples (greedy
        slots ride along on the temperature-0 argmax lane, so the
        bucket stays ONE compiled program with zero retraces)."""
        with _trace("serving::sampled_decode_step"):
            def _timed_decode(tokens, layers, tables, lengths, temps,
                              tks, tps, keys, counters):
                out, pools = self._sampled_decode_step(
                    tokens, layers, tables, lengths, temps, tks, tps,
                    keys, counters)
                return np.asarray(out), pools

            toks, new_pools = self._sampled_wd.call(
                _timed_decode, self._pending[:, None],
                self._target_pools(), bt, self._lengths, self._temps,
                self._top_ks, self._top_ps, self._keys, self._counters)
            self._rebind_target(new_pools)
        self.metrics.on_decode_iteration(
            len(active), self.config.max_batch_size,
            self.pool.utilization())
        for req in active:
            slot = req.slot
            self._lengths[slot] += 1
            next_tok = int(toks[slot])
            req.generated.append(next_tok)
            self._pending[slot] = next_tok
            self._counters[slot] = len(req.generated)
            if not self._emit_token(req, next_tok):
                self._retire(req, "error")
                continue
            self._maybe_retire(req)

    def _spec_iteration(self):
        """One speculative iteration: draft-propose (K tokens, one
        scanned program over the draft's pool slice) → target-verify
        ([S, K+1] chunked-shaped program with on-device acceptance) →
        host commit of each slot's accepted tokens → block-granular KV
        rollback of the rejected tail.  Only the committed token ids
        and accepted lengths sync to host — less per-iteration traffic
        than the greedy step's [S, V] logits."""
        k_draft = self.spec.num_draft_tokens
        self._ensure_blocks(horizon=k_draft + 1)
        active = [r for r in self._slots
                  if r is not None and r.state == RUNNING]
        if not active:
            return
        bt = self._decode_block_view()
        with _trace("serving::spec_step"):
            # draft proposals + distributions stay ON DEVICE between the
            # two steps; the verify closure's np.asarray is the only
            # host sync of the iteration
            def _timed_draft(tokens, layers, tables, lengths, temps,
                             tks, tps, keys, counters):
                return self._draft_propose_step(
                    tokens, layers, tables, lengths, temps, tks, tps,
                    keys, counters)

            props, dprobs, new_draft = self._draft_propose_wd.call(
                _timed_draft, self._pending[:, None], self._draft_pools(),
                bt, self._lengths, self._temps, self._top_ks,
                self._top_ps, self._keys, self._counters)
            self._rebind_draft(new_draft)

            def _timed_verify(pending, proposals, probs, layers, tables,
                              lengths, temps, tks, tps, keys, counters):
                committed, accepted, pools = self._spec_verify_step(
                    pending, proposals, probs, layers, tables, lengths,
                    temps, tks, tps, keys, counters)
                return np.asarray(committed), np.asarray(accepted), pools

            committed, accepted, new_target = \
                self._spec_verify_wd.call(
                    _timed_verify, self._pending, props, dprobs,
                    self._target_pools(), bt, self._lengths, self._temps,
                    self._top_ks, self._top_ps, self._keys,
                    self._counters)
            self._rebind_target(new_target)
        self.metrics.on_decode_iteration(
            len(active), self.config.max_batch_size,
            self.pool.utilization())
        accepted_drafts = 0
        for req in active:
            slot = req.slot
            n_new = int(accepted[slot])          # 1..K+1 committed tokens
            accepted_drafts += n_new - 1
            self.metrics.on_spec_commit(n_new)
            taken = 0
            finished = False
            for tok in committed[slot, :n_new]:
                tok = int(tok)
                req.generated.append(tok)
                taken += 1
                if not self._emit_token(req, tok):
                    self._retire(req, "error")
                    finished = True
                    break
                reason = self.scheduler.finish_reason(req)
                if reason is not None:
                    # eos / stop / length may land mid-commit: trailing
                    # committed tokens are DROPPED, matching where
                    # sequential generate() stops — zero lost, zero
                    # duplicated (_retire frees every block)
                    self._retire(req, reason)
                    finished = True
                    break
            if finished:
                continue
            self._lengths[slot] += taken
            self._pending[slot] = int(committed[slot, taken - 1])
            self._counters[slot] = len(req.generated)
            self._rollback_blocks(req)
        self.metrics.on_spec_step(k_draft * len(active), accepted_drafts)

    def _rollback_blocks(self, req: Request):
        """Truncate ``req``'s KV back to its accepted frontier: blocks
        wholly past the next write position were only ever filled with
        rejected draft KV — free them (refcount drop; they were made
        writable, hence singly-owned, by ``_ensure_blocks``).  Positions
        within kept blocks need no scrub: paged attention masks
        ``k_pos <= q_pos``, so KV past the frontier is never read and
        the next verify overwrites it."""
        keep = self.pool.blocks_for(int(self._lengths[req.slot]) + 1)
        if len(req.blocks) > keep:
            tail = req.blocks[keep:]
            del req.blocks[keep:]
            self.pool.free(tail, req.request_id)
            self._block_tables[req.slot, keep:] = 0

    # ----------------------------------------------------------- retire
    def _maybe_retire(self, req: Request):
        reason = self.scheduler.finish_reason(req)
        if reason is not None:
            self._retire(req, reason)

    def _retire(self, req: Request, reason: str):
        """Finish ``req`` for ``reason`` from ANY state — running in a
        slot, mid-prefill, or never admitted (queued timeout / failed
        prefill).  Releasing its references may PARK prompt blocks in
        the pool's prefix LRU rather than freeing them — that is the
        cache, not a leak."""
        slot = req.slot
        req.state = FINISHED
        req.finish_reason = reason
        if req in self.scheduler.running:
            self.scheduler.running.remove(req)
        self.pool.free_request(req.request_id)
        req.slot = None
        if slot is not None:
            self._slots[slot] = None
            self._block_tables[slot] = 0
            self._lengths[slot] = 0
            self._pending[slot] = 0
            self._clear_sampling_slot(slot)
        self.metrics.on_finish(req.request_id, req.num_generated, reason)
        if req.on_token is not None:
            self.metrics.on_stream_end()
        self._finished[req.request_id] = req

    # ------------------------------------------------------------ misc
    def _sync_pool_metrics(self):
        """Mirror pool-owned prefix-cache counters into the metrics
        layer (delta-based: the pool counts, metrics accumulate)."""
        d = self.pool.evictions - self._evictions_seen
        if d:
            self._evictions_seen = self.pool.evictions
            self.metrics.on_evictions(d)

    def decode_cache_size(self) -> int:
        """Entries in the compiled decode step's jit cache — 1 after
        warmup, forever (the no-retrace contract)."""
        return self._decode_step._cache_size()

    def prefill_cache_size(self) -> int:
        """Entries in the compiled chunked-prefill step's jit cache — 1
        after warmup, for EVERY prompt length (the bucket-explosion
        fix)."""
        return self._prefill_step._cache_size()

    def sampled_decode_cache_size(self) -> int:
        """Jit-cache entries of the sampled decode step — 0 for a
        greedy-only workload (the step never runs), 1 after the first
        sampled iteration, forever (the same no-retrace contract)."""
        return self._sampled_decode_step._cache_size()

    def spec_cache_sizes(self) -> Dict[str, int]:
        """Jit-cache entries of the speculative steps (each 1 after
        warmup) — empty dict when speculation is off."""
        if self.spec is None:
            return {}
        return {"draft_prefill": self._draft_prefill_step._cache_size(),
                "draft_propose": self._draft_propose_step._cache_size(),
                "spec_verify": self._spec_verify_step._cache_size()}

    def health(self) -> dict:
        """Engine health snapshot (serving/overload.py): state
        (``"serving"``/``"degraded"``/``"failed"``), degradation-ladder
        level, watchdog stall/retry totals, latency EWMAs, queue depth
        and KV pressure — host-side only, cheap to poll."""
        return self.overload.snapshot(self)

    def revive(self):
        """Operator override after a FAILED quarantine (step watchdog
        out of retries): clear health back to SERVING so ``submit`` and
        ``step`` accept work again.  The caller owns deciding the
        underlying fault is gone."""
        self.overload.health.revive()

    def pending_prefill_tokens(self) -> int:
        """Prompt tokens admitted-but-uncomputed plus everything still
        waiting in the queue — the prefill backlog a new arrival queues
        behind.  The router's load signal and the TTFT estimator's
        numerator (serving/overload.py) read the same number."""
        pending = sum(r.prompt_len - r.prefill_pos
                      for r in self.scheduler.running
                      if r.state == PREFILLING)
        pending += sum(r.prompt_len for r in self.scheduler.waiting)
        return pending

    def stats(self) -> dict:
        d = self.metrics.as_dict()
        d["pool"] = self.pool.stats()
        d["queue_depth"] = len(self.scheduler.waiting)
        d["pending_prefill_tokens"] = self.pending_prefill_tokens()
        d["prefix_index"] = self.pool.prefix_summary()
        d["health"] = self.health()
        return d
