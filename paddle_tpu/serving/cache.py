# lint-tpu: disable-file=L004 -- serving owns the block-pool device
# buffers directly (like models/); new backend code belongs under core/
# ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Block-based KV-cache pool with content-addressed prefix caching
(PAPERS.md: vLLM's PagedAttention memory manager + RadixAttention-style
prefix reuse, layered on models/llama.py PagedKVCache semantics).

The pool owns per-layer (k, v) device buffers of shape
``[num_blocks, block_size, kv_heads, head_dim]``.  Sequences own
BLOCKS, not contiguous buffer ranges: a free-list allocator hands out
``block_size``-token blocks one at a time as a sequence's frontier
grows, so cache capacity is packed at block granularity instead of
being reserved at worst-case length per request.

Prefix caching adds three structures on top of the free list:

- **refcounts** — ``_owners[block]`` is the SET of request ids holding
  the block, so two requests sharing a system prompt reference the same
  physical blocks (``free`` decrements; the block is recycled only when
  the last owner lets go);
- **chained content hashes** — a full block of prompt tokens is indexed
  by ``hash(parent_hash || block token ids)``, so a block's identity
  encodes its whole prefix: matching block i implies blocks 0..i-1
  matched too, exactly the chain vLLM/SGLang key their prefix caches
  on.  Only FULL blocks are ever registered (a partial tail is private
  to its request);
- **LRU eviction** — a block whose last owner releases it but whose
  content is still indexed parks in an LRU list instead of the free
  list.  It stays matchable for free until ``allocate`` runs dry, at
  which point the least-recently-parked cached block is evicted (index
  entry dropped) and recycled.  Live-referenced blocks are NEVER
  eviction candidates.

Registered blocks are IMMUTABLE: a request that must write inside one
(shared decode tail, or recomputing the last token of a fully-cached
prompt) first breaks the share with :meth:`ensure_writable` — a
copy-on-write device copy into a private block.

Block 0 is a reserved garbage sink: idle engine slots decode with
block-table entries pointing at it, so the compiled step never needs a
host-side branch on "is this slot live" (the write lands in garbage,
attention masks it, and the hot loop stays device-resident — H106).
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..kernels.kv_quant import (kv_bytes_per_element,
                                kv_scale_bytes_per_block,
                                kv_storage_dtype, resolve_kv_cache_dtype)


class PoolExhausted(Exception):
    """No free or evictable blocks: the caller must preempt or wait."""


class BlockKVPool:
    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 kv_heads: int, head_dim: int, dtype=jnp.float32,
                 enable_prefix_cache: bool = True,
                 kv_cache_dtype: Optional[str] = None):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the reserved "
                             "garbage sink)")
        self.num_layers = num_layers
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        #: quant scheme: None (full precision) / "int8" / "fp8"
        self.kv_cache_dtype = resolve_kv_cache_dtype(kv_cache_dtype)
        #: the MODEL's kv dtype (what dequant produces / fp32 pools hold)
        self.model_dtype = dtype
        #: the STORAGE dtype the pool arrays actually carry
        self.dtype = kv_storage_dtype(self.kv_cache_dtype) or dtype
        self.enable_prefix_cache = enable_prefix_cache
        # content-hash chains are seeded with the dtype tag, so an int8
        # pool can never match blocks registered under an fp32 config
        # (or the other scheme) — the seed IS the namespace
        self._hash_seed = self.kv_dtype_tag.encode()
        z = jnp.zeros((num_blocks, block_size, kv_heads, head_dim),
                      self.dtype)
        # per-layer physical pools — the arrays handed to the compiled
        # decode step and rebound to its outputs every token.  Entries
        # are (k, v) for full-precision pools and (k, v, k_scale,
        # v_scale) for quantized ones: int8 code pools plus one f32
        # absmax scale per (block, token) row (kernels/kv_quant.py)
        if self.kv_cache_dtype is not None:
            s = jnp.ones((num_blocks, block_size), jnp.float32)
            self.layers: List[Tuple[jax.Array, ...]] = [
                (z, z, s, s) for _ in range(num_layers)]
        else:
            self.layers = [(z, z) for _ in range(num_layers)]
        # LIFO free list over blocks 1..n-1 (block 0 reserved)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        # block id -> set of owning request ids (refcount = len)
        self._owners: Dict[int, Set] = {}
        # content index: chain hash -> block id, and its reverse.
        # Invariant: b in _block_hash  <=>  _hash_index[_block_hash[b]] == b
        self._hash_index: Dict[bytes, int] = {}
        self._block_hash: Dict[int, bytes] = {}
        # refcount-0 blocks still holding indexed content, oldest first —
        # matchable for free, evictable when the free list runs dry
        self._cached_free: "OrderedDict[int, None]" = OrderedDict()
        # chain ROOTS (depth-1 hashes), most recently registered last —
        # the cheap recency signal prefix_summary() exposes to a fleet
        # router (every cached prompt family is reachable through one)
        self._roots: "OrderedDict[bytes, None]" = OrderedDict()
        self.evictions = 0
        self.cow_copies = 0

    # ------------------------------------------------------- accounting
    @property
    def capacity_blocks(self) -> int:
        """Allocatable blocks (excludes the reserved garbage block)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        """Blocks allocatable RIGHT NOW: truly free plus cached-but-
        unreferenced (the latter evict on demand)."""
        return len(self._free) + len(self._cached_free)

    @property
    def num_used(self) -> int:
        """Blocks referenced by at least one live request."""
        return self.capacity_blocks - self.num_free

    @property
    def num_cached(self) -> int:
        """Unreferenced blocks kept alive only by the prefix index."""
        return len(self._cached_free)

    def utilization(self) -> float:
        return self.num_used / self.capacity_blocks

    # --------------------------------------------------- byte accounting
    @property
    def kv_dtype_tag(self) -> str:
        """Stable string identity of this pool's KV storage format —
        the prefix-cache hash namespace and the router's fleet-dtype
        key (``"int8"``, ``"fp8"``, or ``"fp32:<model dtype>"``)."""
        if self.kv_cache_dtype is not None:
            return self.kv_cache_dtype
        return f"fp32:{jnp.dtype(self.model_dtype).name}"

    @staticmethod
    def block_bytes_for(num_layers: int, block_size: int, kv_heads: int,
                        head_dim: int, dtype=jnp.float32,
                        kv_cache_dtype: Optional[str] = None) -> int:
        """HBM bytes ONE logical block costs across all layers (k and v
        pools plus quantized scale sidecars) — computable before the
        pool exists, so the engine can size ``num_blocks`` from a fixed
        ``kv_pool_bytes`` budget per dtype."""
        scheme = resolve_kv_cache_dtype(kv_cache_dtype)
        esize = kv_bytes_per_element(scheme, dtype)
        per_side = block_size * kv_heads * head_dim * esize \
            + kv_scale_bytes_per_block(block_size, scheme)
        return int(num_layers * 2 * per_side)

    def block_bytes(self) -> int:
        """HBM bytes one block costs in THIS pool (all layers, k + v,
        including quantized scale rows)."""
        return self.block_bytes_for(self.num_layers, self.block_size,
                                    self.kv_heads, self.head_dim,
                                    self.model_dtype, self.kv_cache_dtype)

    def capacity_bytes(self) -> int:
        return self.capacity_blocks * self.block_bytes()

    def used_bytes(self) -> int:
        """Bytes referenced by live requests — the quantity degradation
        watermarks compare against :meth:`capacity_bytes` (a quantized
        pool burns ~4x fewer bytes per resident token, so the ladder
        engages later at the same request load)."""
        return self.num_used * self.block_bytes()

    def byte_utilization(self) -> float:
        """Fraction of the pool's KV byte capacity referenced by live
        requests.  Blocks are homogeneous within one pool so this equals
        :meth:`utilization` numerically, but it is the BYTE-denominated
        pressure signal: two pools sized from the same ``kv_pool_bytes``
        budget at different dtypes report comparable pressure per byte,
        not per block."""
        return self.used_bytes() / self.capacity_bytes()

    def blocks_for(self, num_tokens: int) -> int:
        """Blocks needed to hold ``num_tokens`` cache positions."""
        return -(-int(num_tokens) // self.block_size)

    def can_allocate(self, n: int) -> bool:
        return self.num_free >= n

    def owned_by(self, request_id) -> List[int]:
        return [b for b, o in self._owners.items() if request_id in o]

    def refcount(self, block: int) -> int:
        return len(self._owners.get(block, ()))

    def is_shared(self, block: int) -> bool:
        """True when a write into ``block`` would be observable outside
        the writing request: another owner holds it, or the prefix index
        still advertises its content to future requests."""
        return len(self._owners.get(block, ())) > 1 \
            or block in self._block_hash

    # ------------------------------------------------------- allocation
    def allocate(self, request_id, n: int = 1) -> List[int]:
        """Hand ``n`` private blocks to ``request_id``, evicting LRU
        cached blocks if the free list alone cannot cover the request.
        Raises :class:`PoolExhausted` (allocating nothing) otherwise."""
        if self.num_free < n:
            raise PoolExhausted(
                f"need {n} block(s), {len(self._free)} free + "
                f"{len(self._cached_free)} evictable "
                f"(capacity {self.capacity_blocks})")
        blocks = []
        for _ in range(n):
            b = self._free.pop() if self._free else self._evict_lru()
            self._owners[b] = {request_id}
            blocks.append(b)
        return blocks

    def _evict_lru(self) -> int:
        """Drop the least-recently-parked cached block from the prefix
        index and recycle it.  Only refcount-0 blocks ever sit in
        ``_cached_free``, so a live request's block can never be chosen."""
        b, _ = self._cached_free.popitem(last=False)
        h = self._block_hash.pop(b, None)
        if h is not None and self._hash_index.get(h) == b:
            del self._hash_index[h]
            self._roots.pop(h, None)
        self.evictions += 1
        return b

    def evict_parked(self, n: Optional[int] = None) -> int:
        """Eagerly evict up to ``n`` (default: all) PARKED prefix-cache
        blocks, LRU-first, returning them to the free list.  The
        degradation ladder's first rung (serving/overload.py): parked
        blocks already count as allocatable headroom (``num_free``), but
        reclaiming them up front makes the headroom real before a burst
        of allocations has to evict one block at a time — and drops the
        stale prefix index entries with them.  Returns the number
        evicted."""
        count = 0
        while self._cached_free and (n is None or count < n):
            self._free.append(self._evict_lru())
            count += 1
        return count

    def _release_block(self, b: int):
        """Last owner gone: park indexed content in the LRU, recycle the
        rest."""
        self._owners.pop(b, None)
        if self.enable_prefix_cache and b in self._block_hash:
            self._cached_free[b] = None     # LRU tail = most recent
        else:
            self._free.append(b)

    def free(self, blocks: Sequence[int], request_id=None):
        """Drop ``request_id``'s reference on each block (refcount
        decrement); a block with no owners left is recycled.  Without a
        ``request_id`` the block must be singly-owned (the pre-refcount
        call shape); freeing a block the id does not own — or freeing an
        unowned block — is the classic double free, reported with the
        CURRENT owner set to ease debugging."""
        for b in blocks:
            owners = self._owners.get(b)
            if owners is None:
                raise ValueError(
                    f"double free of block {b} (no current owner)")
            if request_id is None:
                if len(owners) > 1:
                    raise ValueError(
                        f"block {b} is shared (owned by "
                        f"{sorted(map(str, owners))}); "
                        f"free(..., request_id=...) required")
                owners.clear()
            else:
                if request_id not in owners:
                    raise ValueError(
                        f"double free of block {b} by {request_id!r} "
                        f"(owned by {sorted(map(str, owners))})")
                owners.discard(request_id)
            if not owners:
                self._release_block(b)

    def free_request(self, request_id):
        """Release every block ``request_id`` references.  A request
        owning nothing (never prefilled, or already released) is a safe
        no-op — retire paths call this unconditionally.

        Blocks release in REVERSE acquisition order, so a prompt
        chain's tail blocks park in the LRU before its head: under
        pressure eviction then consumes leaves first, and the head —
        which ANY extension of the prefix can reuse, where a tail only
        serves exact matches — survives longest (the radix-tree
        leaf-first eviction order of the prefix-caching literature)."""
        blocks = self.owned_by(request_id)
        if not blocks:
            return
        self.free(list(reversed(blocks)), request_id)

    def check_leaks(self):
        """Raise if any block is still owned by a request — used by
        tests and engine shutdown to prove references round-trip.
        Cached-but-unreferenced blocks are NOT leaks (they are
        reclaimable on demand)."""
        if self._owners:
            raise AssertionError(
                "leaked blocks: "
                f"{sorted((b, sorted(map(str, o))) for b, o in self._owners.items())}")

    # ---------------------------------------------------- prefix cache
    @staticmethod
    def _chain_hash(parent: bytes, tokens: np.ndarray) -> bytes:
        h = hashlib.blake2b(parent, digest_size=16)
        h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
        return h.digest()

    def hash_chain(self, tokens) -> List[bytes]:
        """Chained content hashes of every FULL block of ``tokens``:
        ``chain[i] = H(chain[i-1] || tokens[i*bs:(i+1)*bs])``.  A match
        on chain[i] therefore implies the entire prefix matched."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        out: List[bytes] = []
        parent = self._hash_seed
        for i in range(len(tokens) // bs):
            parent = self._chain_hash(parent, tokens[i * bs:(i + 1) * bs])
            out.append(parent)
        return out

    def match_prefix(self, tokens) -> List[int]:
        """Longest indexed prefix of ``tokens``, as a block-id list
        (full blocks only; stops at the first miss).  Pure lookup: no
        refcounts move until :meth:`acquire`."""
        if not self.enable_prefix_cache:
            return []
        out: List[int] = []
        for h in self.hash_chain(tokens):
            b = self._hash_index.get(h)
            if b is None:
                break
            out.append(b)
        return out

    def acquire(self, request_id, blocks: Sequence[int]):
        """Add ``request_id``'s reference to already-populated blocks
        (a prefix-cache hit).  Blocks parked in the LRU come back to
        life; blocks some other request still owns just gain an owner."""
        for b in blocks:
            owners = self._owners.get(b)
            if owners is not None:
                owners.add(request_id)
            elif b in self._cached_free:
                del self._cached_free[b]
                self._owners[b] = {request_id}
            else:
                raise ValueError(
                    f"cannot acquire block {b}: neither owned nor cached")

    def register_prefix(self, request_id, tokens, blocks: Sequence[int]
                        ) -> int:
        """Index ``request_id``'s prompt blocks by content so future
        prompts can reuse them.  Dedupes against existing entries (first
        writer wins — identical content, either block serves) and skips
        blocks the request does not own (defensive: CoW may have
        retired them mid-prefill).  Returns how many entries were added.
        Registered blocks become immutable until evicted."""
        if not self.enable_prefix_cache:
            return 0
        added = 0
        chain = self.hash_chain(tokens)
        for h, b in zip(chain, blocks):
            if h in self._hash_index or b in self._block_hash:
                continue
            owners = self._owners.get(b)
            if owners is None or request_id not in owners:
                continue
            self._hash_index[h] = b
            self._block_hash[b] = h
            added += 1
        # refresh root recency: depth-1 hash of an indexed chain is the
        # entry point any prompt sharing this prefix family matches
        # through (re-registering moves it to most-recent)
        if chain and chain[0] in self._hash_index:
            self._roots.pop(chain[0], None)
            self._roots[chain[0]] = None
        return added

    def ensure_writable(self, request_id, block: int) -> int:
        """Copy-on-write guard: return a block ``request_id`` may write
        in place — ``block`` itself when exclusively owned and not in
        the prefix index, otherwise a fresh private copy (device copy of
        all layers; the request's reference moves to the copy)."""
        owners = self._owners.get(block)
        if owners is None or request_id not in owners:
            raise ValueError(
                f"{request_id!r} does not own block {block}")
        if len(owners) == 1 and block not in self._block_hash:
            return block
        new = self.allocate(request_id, 1)[0]
        self._copy_block(block, new)
        owners.discard(request_id)
        if not owners:
            self._release_block(block)
        self.cow_copies += 1
        return new

    def _copy_block(self, src: int, dst: int):
        new = _copy_block_impl(tuple(self.layers), np.int32(src),
                               np.int32(dst))
        self.layers = [tuple(entry) for entry in new]

    def admission_plan(self, tokens, extra_tokens: int = 1):
        """Admission-control view of one prompt: ``(matched_blocks,
        new_blocks_needed, feasible_now)``.  Matched blocks that sit in
        the evictable LRU are NOT double-counted as allocatable — the
        hit consumes them."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        matched = self.match_prefix(tokens)
        need = self.blocks_for(len(tokens) + extra_tokens) - len(matched)
        need = max(need, 0)
        from_lru = sum(1 for b in matched if b in self._cached_free)
        return matched, need, need <= self.num_free - from_lru

    def stats(self) -> dict:
        return {
            "capacity_blocks": self.capacity_blocks,
            "used_blocks": self.num_used,
            "free_blocks": self.num_free,
            "cached_blocks": self.num_cached,
            "block_size": self.block_size,
            "utilization": round(self.utilization(), 4),
            "prefix_evictions": self.evictions,
            "cow_copies": self.cow_copies,
            "kv_dtype": self.kv_dtype_tag,
            "block_bytes": self.block_bytes(),
            "used_bytes": self.used_bytes(),
            "capacity_bytes": self.capacity_bytes(),
            "byte_utilization": round(self.byte_utilization(), 4),
        }

    def prefix_summary(self, max_roots: int = 8) -> dict:
        """Host-side summary of the prefix index for a FLEET ROUTER
        (serving/router.py): enough to score a candidate prompt's
        expected cached-token count on this pool WITHOUT reaching into
        pool internals.  ``hashes`` is every indexed chain hash (hex; at
        most ``capacity_blocks`` 16-byte digests, so the summary stays
        cheap); a router chains the prompt with :meth:`hash_chain` and
        counts leading members — the same stop-at-first-miss walk
        :meth:`match_prefix` performs.  ``roots`` are the most recently
        registered depth-1 hashes (recent-first): the coarse "which
        prompt families live here" signal for dashboards and logs."""
        roots = [h.hex() for h in reversed(self._roots)]
        return {
            "block_size": self.block_size,
            "kv_dtype": self.kv_dtype_tag,
            "cached_blocks": self.num_cached,
            "indexed_blocks": len(self._hash_index),
            "roots": roots[:max_roots],
            "hashes": [h.hex() for h in self._hash_index],
        }


@jax.jit
def _copy_block_impl(layers, src, dst):
    # one executable per pool geometry: src/dst ride in as traced
    # scalars.  Entries are (k, v) or (k, v, k_scale, v_scale) — a CoW
    # copy of a quantized block must move the scale rows with the codes
    return [tuple(a.at[dst].set(a[src]) for a in entry)
            for entry in layers]
