# lint-tpu: disable-file=L004 -- serving owns the block-pool device
# buffers directly (like models/); new backend code belongs under core/
# ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Block-based KV-cache pool (PAPERS.md: vLLM's PagedAttention memory
manager, layered on models/llama.py StaticKVCache semantics).

The pool owns per-layer (k, v) device buffers of shape
``[num_blocks, block_size, kv_heads, head_dim]``.  Sequences own
BLOCKS, not contiguous buffer ranges: a free-list allocator hands out
``block_size``-token blocks one at a time as a sequence's frontier
grows, so cache capacity is packed at block granularity instead of
being reserved at worst-case length per request — the memory headroom
that lets continuous batching run many more concurrent sequences than
``max_batch * max_len`` preallocation would.

Block 0 is a reserved garbage sink: idle engine slots decode with
block-table entries pointing at it, so the compiled step never needs a
host-side branch on "is this slot live" (the write lands in garbage,
attention masks it, and the hot loop stays device-resident — H106).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp


class PoolExhausted(Exception):
    """No free blocks: the caller must preempt or wait."""


class BlockKVPool:
    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 kv_heads: int, head_dim: int, dtype=jnp.float32):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the reserved "
                             "garbage sink)")
        self.num_layers = num_layers
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        z = jnp.zeros((num_blocks, block_size, kv_heads, head_dim), dtype)
        # per-layer (k, v) physical pools — the arrays handed to the
        # compiled decode step and rebound to its outputs every token
        self.layers: List[Tuple[jax.Array, jax.Array]] = [
            (z, z) for _ in range(num_layers)]
        # LIFO free list over blocks 1..n-1 (block 0 reserved)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._owner: Dict[int, object] = {}   # block id -> request id

    # ------------------------------------------------------- accounting
    @property
    def capacity_blocks(self) -> int:
        """Allocatable blocks (excludes the reserved garbage block)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.capacity_blocks - len(self._free)

    def utilization(self) -> float:
        return self.num_used / self.capacity_blocks

    def blocks_for(self, num_tokens: int) -> int:
        """Blocks needed to hold ``num_tokens`` cache positions."""
        return -(-int(num_tokens) // self.block_size)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def owned_by(self, request_id) -> List[int]:
        return [b for b, o in self._owner.items() if o == request_id]

    # ------------------------------------------------------- allocation
    def allocate(self, request_id, n: int = 1) -> List[int]:
        if len(self._free) < n:
            raise PoolExhausted(
                f"need {n} block(s), {len(self._free)} free "
                f"(capacity {self.capacity_blocks})")
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._owner[b] = request_id
        return blocks

    def free(self, blocks: Sequence[int]):
        for b in blocks:
            owner = self._owner.pop(b, None)
            if owner is None:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)

    def free_request(self, request_id):
        self.free(self.owned_by(request_id))

    def check_leaks(self):
        """Raise if any block is still owned — used by tests and engine
        shutdown to prove the free-list round-trips."""
        if self._owner:
            raise AssertionError(
                f"leaked blocks: {sorted(self._owner.items())}")

    # ------------------------------------------------------ device data
    def install_prefill(self, blocks: Sequence[int], prefill_caches):
        """Copy a prompt's prefilled StaticKVCache buffers
        (``[(k, v)]`` per layer, each ``[1, len(blocks)*block_size, kv,
        hd]``) into the owned pool blocks.  Shapes vary only with
        ``len(blocks)``, so jit holds one executable per prompt-block
        count (prefill-side; the decode step itself never retraces)."""
        idx = jnp.asarray(list(blocks), jnp.int32)
        new = _install_impl(tuple(self.layers),
                            tuple((k, v) for k, v in prefill_caches), idx)
        self.layers = [(k, v) for k, v in new]

    def stats(self) -> dict:
        return {
            "capacity_blocks": self.capacity_blocks,
            "used_blocks": self.num_used,
            "free_blocks": self.num_free,
            "block_size": self.block_size,
            "utilization": round(self.utilization(), 4),
        }


@jax.jit
def _install_impl(layers, prefill, idx):
    out = []
    for (pk, pv), (fk, fv) in zip(layers, prefill):
        n = idx.shape[0]
        bs = pk.shape[1]
        out.append((
            pk.at[idx].set(fk[0].reshape(n, bs, fk.shape[2], fk.shape[3])
                           .astype(pk.dtype)),
            pv.at[idx].set(fv[0].reshape(n, bs, fv.shape[2], fv.shape[3])
                           .astype(pv.dtype)),
        ))
    return out
