"""Server-sent-events framing over the serving engine — the thin
streaming front door (ISSUE 19c).  No external deps: an SSE response is
just an iterator of ``data: <json>\\n\\n`` frames, which is exactly what
this module yields, so any WSGI/ASGI shim (or a test) can drain it.

Token delivery rides the engine's ``on_token`` callback
(``Engine.submit(on_token=...)`` fires once per ACCEPTED token — under
speculative decoding a single engine iteration may fire several times),
and per-token deadlines (``token_deadline_s``) thread into the existing
shed/priority machinery: a stream that stalls past its inter-token
deadline times out and degrades instead of queueing forever.
"""
from __future__ import annotations

import json
from collections import deque
from typing import Iterator

DONE_FRAME = "data: [DONE]\n\n"


def sse_event(payload) -> str:
    """One SSE frame: ``data: <compact json>`` + blank line."""
    return f"data: {json.dumps(payload, separators=(',', ':'))}\n\n"


def stream_events(target, prompt, **submit_kwargs) -> Iterator[dict]:
    """Submit ``prompt`` and yield one dict per generated token
    (``{"token": id, "index": i}``) while driving the engine, then a
    final ``{"finish_reason": ..., "num_tokens": ..., "request_id":
    ...}`` summary event.

    ``target`` is anything engine-shaped: an :class:`Engine`, a
    :class:`~paddle_tpu.serving.router.Router`, or an
    :class:`~paddle_tpu.serving.endpoint.Endpoint`.  Other requests
    already in flight keep making progress — the drive loop is the
    ordinary ``step()``/``poll()`` tick, streaming just drains this
    request's tokens as they land."""
    tick = getattr(target, "poll", None) or target.step
    buf: deque = deque()
    req = target.submit(prompt, on_token=buf.append, **submit_kwargs)
    index = 0
    from .scheduler import FINISHED

    while True:
        while buf:
            yield {"token": int(buf.popleft()), "index": index}
            index += 1
        if req.state == FINISHED:
            break
        if not tick() and not buf and req.state != FINISHED:
            break           # engine drained without finishing (shed)
    while buf:
        yield {"token": int(buf.popleft()), "index": index}
        index += 1
    yield {"finish_reason": req.finish_reason, "num_tokens": index,
           "request_id": req.request_id}


def sse_stream(target, prompt, **submit_kwargs) -> Iterator[str]:
    """:func:`stream_events` framed as SSE ``data:`` lines, terminated
    by the OpenAI-style ``data: [DONE]`` sentinel."""
    for event in stream_events(target, prompt, **submit_kwargs):
        yield sse_event(event)
    yield DONE_FRAME
