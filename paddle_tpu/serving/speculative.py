# lint-tpu: disable-file=L004 -- serving drives the compiled decode/
# prefill steps over raw device buffers (like models/); new backend code
# belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Speculative decoding: a small draft model proposes K tokens per
target step; the target verifies all K+1 positions in ONE
chunked-prefill-shaped program (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding"; reuse of the PR 5/14 chunked
program and the prefix cache is the point of ISSUE 19).

Two compiled steps, both in the decode-step registry:

- ``draft_propose``: K sequential draft forwards inside one
  ``lax.scan`` — ONE compiled program per engine config, writing the
  draft's KV into its own layer slice of the shared block pool, and
  emitting the proposals plus the draft's full filtered distributions
  (needed for rejection sampling).
- ``spec_verify``: one batched [S, K+1] target forward over
  ``[pending, d1..dK]`` at positions ``P..P+K`` (the chunked-prefill
  attention shape), then ON-DEVICE acceptance:

  * greedy lanes (``temperature == 0``): proposal ``d_{j+1}`` is
    accepted iff it equals the target argmax at position j; the first
    mismatch position contributes the target's own argmax as the
    correction token — so the committed tokens are exactly the greedy
    continuation, token-for-token what ``generate()`` emits.
  * sampled lanes: standard rejection sampling — accept ``d`` with
    probability ``min(1, p(d)/q(d))`` (target / draft filtered probs,
    uniforms keyed by the per-token fold + ACCEPT_TAG); on rejection
    resample from the residual ``normalize(max(p - q, 0))``; when all K
    drafts survive, a bonus token samples from the target distribution
    at position K.  Every key derives from the request's base key and
    TOKEN INDEX, so preemption + recompute replays identically.

  Only ``(committed [S, K+1], accepted_len [S])`` sync to host — less
  traffic than the greedy step's [S, V] logits sync.

KV bookkeeping is the engine's job: the verify step writes target KV
for all K+1 positions; the engine truncates each slot back to its
accepted length (block-table tail positions are simply never attended —
the paged attention masks ``k_pos <= q_pos``) and frees whole blocks
past the new frontier, so rejected drafts leak nothing.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..models.generation import (_cache_dims, _fingerprint_matches,
                                 _weights_fingerprint, register_decode_step)
from .sampling import (ACCEPT_TAG, BONUS_TAG, DRAFT_TAG, filtered_probs,
                       fold_keys, sample_tokens)


@dataclass
class SpeculativeConfig:
    """``ServingConfig.speculative``: the draft model (same
    ``LlamaConfig`` family — must share vocab, kv-head count, head_dim
    and cache dtype with the target so both live in one
    :class:`~paddle_tpu.serving.cache.BlockKVPool`) and the number of
    draft tokens proposed per target verify step."""

    draft_model: Any
    num_draft_tokens: int = 4

    def __post_init__(self):
        if self.num_draft_tokens < 1:
            raise ValueError("num_draft_tokens must be >= 1, got "
                             f"{self.num_draft_tokens}")

    def validate_against(self, model):
        """Both models' KV slices share one block pool (that is what
        lets the prefix cache serve draft and target from the same
        blocks), so the per-position cache geometry must match."""
        if _cache_dims(self.draft_model) != _cache_dims(model):
            raise ValueError(
                "draft/target cache layouts differ "
                f"(draft {_cache_dims(self.draft_model)} vs target "
                f"{_cache_dims(model)}): speculative decoding shares one "
                "BlockKVPool, so kv_heads, head_dim and dtype must match")
        dv = self.draft_model.config.vocab_size
        tv = model.config.vocab_size
        if dv != tv:
            raise ValueError(f"draft vocab {dv} != target vocab {tv}: "
                             "speculative decoding needs a shared "
                             "tokenizer")


def make_draft_propose_step(draft_model, num_draft, fused=None):
    """step(tok[S, 1] int32, pools, block_tables[S, max_blocks] int32,
    lengths[S] int32, temps[S] f32, top_ks[S] int32, top_ps[S] f32,
    keys[S, 2] uint32, counters[S] int32) -> (proposals[S, K] int32,
    draft_probs[S, K, V] f32, new_pools).

    K+1 sequential single-token draft decodes under one ``lax.scan`` —
    one fused program, no host syncs between draft tokens.  The scan
    runs one iteration PAST the last proposal: iteration K feeds
    ``d_K`` back in purely to write its KV into the draft's pool slice
    (its proposal is discarded).  Without that, a fully-accepted window
    commits ``d_K`` at position ``lengths + K`` while the draft cache
    has no entry there — every later draft forward would attend garbage
    at that hole and mispropose forever after.  Draft token j for a
    request whose next token index is i uses key
    ``fold(fold(base, i + j), DRAFT_TAG)``: greedy lanes argmax, so a
    weight-identical draft reproduces the target's greedy continuation
    exactly (the accept-rate ceiling the bench measures)."""
    from ..core.dispatch import no_grad_ctx
    from ..kernels.fusion import resolve_serving_fusion, serving_fusion
    from ..models.llama import PagedKVCache

    fused = resolve_serving_fusion(fused)
    attr = f"_draft_propose_step_{num_draft}" + ("_fused" if fused else "")
    step = getattr(draft_model, attr, None)
    if step is not None and _fingerprint_matches(
            draft_model, getattr(draft_model, attr + "_fp", None)):
        return step
    fp = _weights_fingerprint(draft_model)

    @jax.jit
    @functools.partial(register_decode_step, kind="draft_propose")
    def step(tok, pools, block_tables, lengths, temps, top_ks, top_ps,
             keys, counters):
        with no_grad_ctx(), serving_fusion(fused):
            def propose(carry, i):
                cur, layers = carry
                wrapped = [PagedKVCache(k, v, block_tables)
                           for k, v in layers]
                logits, new_caches = draft_model(
                    Tensor(cur), caches=wrapped,
                    position_offset=lengths + i)
                last = logits._value[:, -1].astype(jnp.float32)
                step_keys = fold_keys(fold_keys(keys, counters + i),
                                      DRAFT_TAG)
                nxt = sample_tokens(last, temps, top_ks, top_ps,
                                    step_keys)
                probs = filtered_probs(last, temps, top_ks, top_ps)
                return ((nxt[:, None], [(c.k, c.v) for c in new_caches]),
                        (nxt, probs))

            (_, layers), (props, probs) = jax.lax.scan(
                propose, (tok, list(pools)), jnp.arange(num_draft + 1))
            return (jnp.transpose(props)[:, :num_draft],
                    jnp.transpose(probs, (1, 0, 2))[:, :num_draft], layers)

    setattr(draft_model, attr, step)
    setattr(draft_model, attr + "_fp", fp)
    return step


def _spec_acceptance(lg, proposals, draft_probs, temps, top_ks, top_ps,
                     keys, counters):
    """On-device acceptance over the verify logits ``lg [S, K+1, V]``.

    Returns ``(committed [S, K+1] int32, accepted_len [S] int32)``:
    row s commits ``committed[s, :accepted_len[s]]`` (accepted drafts
    followed by one bonus/correction token, so ``accepted_len`` is in
    ``1..K+1``); later entries are zero padding."""
    s, k1, v = lg.shape
    k = k1 - 1
    tprobs = filtered_probs(
        lg.reshape(s * k1, v), jnp.repeat(temps, k1),
        jnp.repeat(top_ks, k1), jnp.repeat(top_ps, k1)).reshape(s, k1, v)
    greedy_choice = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    greedy_ok = proposals == greedy_choice[:, :k]
    q = jnp.take_along_axis(draft_probs, proposals[..., None],
                            axis=-1)[..., 0]
    p = jnp.take_along_axis(tprobs[:, :k], proposals[..., None],
                            axis=-1)[..., 0]
    draft_idx = counters[:, None] + jnp.arange(k)[None, :]
    ukeys = fold_keys(fold_keys(
        jnp.broadcast_to(keys[:, None, :], (s, k, 2)), draft_idx),
        ACCEPT_TAG)
    u = jax.vmap(lambda kk: jax.random.uniform(kk, ()))(
        ukeys.reshape(-1, 2)).reshape(s, k)
    stochastic_ok = u * jnp.maximum(q, 1e-20) < p
    ok = jnp.where((temps > 0)[:, None], stochastic_ok, greedy_ok)
    acc = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    n = jnp.sum(acc, axis=1)                        # accepted drafts 0..K
    # bonus token at position n: residual resample on rejection, fresh
    # target sample when every draft survived
    t_at = jnp.take_along_axis(tprobs, n[:, None, None], axis=1)[:, 0]
    dpad = jnp.concatenate(
        [draft_probs, jnp.zeros((s, 1, v), draft_probs.dtype)], axis=1)
    d_at = jnp.take_along_axis(dpad, n[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(t_at - d_at, 0.0)
    rsum = resid.sum(-1, keepdims=True)
    use_resid = (n < k)[:, None] & (rsum > 1e-12)
    dist = jnp.where(use_resid, resid / jnp.maximum(rsum, 1e-20), t_at)
    bkeys = fold_keys(fold_keys(keys, counters + n), BONUS_TAG)
    sampled_bonus = jax.vmap(jax.random.categorical)(
        bkeys, jnp.log(dist + 1e-30)).astype(jnp.int32)
    greedy_bonus = jnp.take_along_axis(greedy_choice, n[:, None],
                                       axis=1)[:, 0]
    bonus = jnp.where(temps > 0, sampled_bonus, greedy_bonus)
    pos = jnp.arange(k1)[None, :]
    padded = jnp.concatenate(
        [proposals, jnp.zeros((s, 1), proposals.dtype)], axis=1)
    committed = jnp.where(pos < n[:, None], padded,
                          jnp.where(pos == n[:, None], bonus[:, None], 0))
    return committed.astype(jnp.int32), (n + 1).astype(jnp.int32)


def make_spec_verify_step(model, num_draft, fused=None):
    """step(pending[S] int32, proposals[S, K] int32, draft_probs
    [S, K, V] f32, pools, block_tables[S, max_blocks] int32, lengths[S]
    int32, temps[S] f32, top_ks[S] int32, top_ps[S] f32, keys[S, 2]
    uint32, counters[S] int32) -> (committed[S, K+1] int32,
    accepted_len[S] int32, new_pools).

    The target forward is exactly the chunked-prefill attention shape
    batched over slots ([S, K+1] ids with vector position offsets);
    causal masking means junk KV past a slot's frontier is never read,
    which is what makes writing all K+1 positions and rolling back by
    length truncation safe.  Acceptance (:func:`_spec_acceptance`) stays
    on device; only committed tokens + accepted lengths sync back."""
    from ..core.dispatch import no_grad_ctx
    from ..kernels.fusion import resolve_serving_fusion, serving_fusion
    from ..models.llama import PagedKVCache

    fused = resolve_serving_fusion(fused)
    attr = f"_spec_verify_step_{num_draft}" + ("_fused" if fused else "")
    step = getattr(model, attr, None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, attr + "_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    @jax.jit
    @functools.partial(register_decode_step, kind="spec_verify")
    def step(pending, proposals, draft_probs, pools, block_tables,
             lengths, temps, top_ks, top_ps, keys, counters):
        with no_grad_ctx(), serving_fusion(fused):
            ids = jnp.concatenate(
                [pending[:, None], proposals.astype(pending.dtype)],
                axis=1)
            wrapped = [PagedKVCache(k, v, block_tables) for k, v in pools]
            logits, new_caches = model(Tensor(ids), caches=wrapped,
                                       position_offset=lengths)
            lg = logits._value.astype(jnp.float32)
            committed, accepted = _spec_acceptance(
                lg, proposals, draft_probs, temps, top_ks, top_ps,
                keys, counters)
            return committed, accepted, [(c.k, c.v) for c in new_caches]

    setattr(model, attr, step)
    setattr(model, attr + "_fp", fp)
    return step
