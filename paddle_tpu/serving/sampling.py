# lint-tpu: disable-file=L004 -- serving drives the compiled decode/
# prefill steps over raw device buffers (like models/); new backend code
# belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Traced per-request sampling for the serving engine (reference
capability: paddle/fluid/operators/top_k_op + top_p_sampling_op and
PaddleNLP's ``decode_strategy="sampling"``; here the whole transform is
part of the compiled decode step).

Design constraints (ISSUE 19 / H106):

- The bucket-wide decode step stays ONE compiled program: temperature /
  top-k / top-p are per-slot DEVICE arrays, not trace constants, so a
  bucket mixing greedy and sampled requests (or requests with different
  temperatures) never retraces.
- PRNG state never round-trips to host.  Each request carries a base
  key (``[2] uint32``, from its seed); the step folds the key with the
  request's token counter ON DEVICE (`fold_keys`), so the i-th generated
  token of a request always uses ``fold_in(base, i)`` — independent of
  slot placement, bucket composition, or preemption/recompute history.
  ``generate()`` uses the same schedule, which is what makes the
  engine-vs-generate parity oracle extend to sampled outputs (same seed
  → token-exact).
- Greedy stays the ``temperature == 0`` special case: those lanes take
  ``argmax`` of the raw logits via ``jnp.where``, bit-identical to the
  plain paged-decode step's selection, and an all-greedy engine never
  runs this step at all.

Dynamic per-row top-k: ``lax.top_k`` needs a static k, so rows are
sorted descending and thresholded at their own (clamped) k-th value —
O(V log V) per row, all shapes static.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..models.generation import (_fingerprint_matches, _weights_fingerprint,
                                 register_decode_step)

# key-derivation tags: the draft proposal, acceptance uniform and bonus/
# residual resample for token index i must be independent of the target
# sample for token index i (speculative.py folds these on top of the
# per-token fold), so each purpose gets a second fold with its own tag
DRAFT_TAG = 0x5D
ACCEPT_TAG = 0xAC
BONUS_TAG = 0xB0


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (``Engine.submit(sampling=...)``).

    ``temperature == 0`` means greedy (argmax) — the engine keeps such
    requests on the plain greedy decode step.  ``top_k == 0`` and
    ``top_p == 1.0`` disable those filters.  ``seed=None`` draws the
    request's base key from the framework RNG (deterministic under
    ``paddle.seed``, unique per request); a fixed seed makes the token
    stream reproducible regardless of batching, slot placement or
    preemption."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, "
                             f"got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0

    def base_key(self) -> np.ndarray:
        """The request's base PRNG key as raw ``[2] uint32``."""
        if self.seed is None:
            from ..ops import random as rnd
            return np.asarray(rnd.next_key(), np.uint32)
        return np.asarray(jax.random.PRNGKey(int(self.seed)), np.uint32)


def resolve_sampling(sampling=None, *, temperature=None, do_sample=False,
                     top_k=0, top_p=1.0, seed=None):
    """Normalize the legacy ``generate()``-style knobs and the explicit
    ``SamplingParams`` into one spec.  Returns ``None`` for greedy.

    Shared by ``Engine.submit`` and ``Router.submit`` so both front
    doors accept ``temperature=0.8`` / ``do_sample=True`` (reference
    ``decode_strategy="sampling"`` spelling) as well as
    ``sampling=SamplingParams(...)`` / ``sampling={"temperature": ...}``.
    """
    if sampling is not None:
        if isinstance(sampling, dict):
            sampling = SamplingParams(**sampling)
        if not isinstance(sampling, SamplingParams):
            raise TypeError("sampling= takes a SamplingParams or a dict "
                            f"of its fields, got {type(sampling).__name__}")
        return None if sampling.is_greedy else sampling
    temp = 0.0 if temperature is None else float(temperature)
    if do_sample and temp == 0.0:
        temp = 1.0          # reference default: do_sample alone means T=1
    if temp == 0.0:
        return None
    return SamplingParams(temperature=temp, top_k=int(top_k),
                          top_p=float(top_p), seed=seed)


# ---------------------------------------------------------------------------
# traced transform
# ---------------------------------------------------------------------------

def fold_keys(keys, data):
    """Vectorized ``jax.random.fold_in``: ``keys [..., 2] uint32`` folded
    elementwise with ``data`` (broadcast to the leading dims)."""
    keys = jnp.asarray(keys).astype(jnp.uint32)
    lead = keys.shape[:-1]
    data = jnp.broadcast_to(jnp.asarray(data, jnp.int32), lead)
    flat = jax.vmap(jax.random.fold_in)(keys.reshape(-1, 2),
                                        data.reshape(-1))
    return flat.reshape(lead + (2,))


def filter_logits(logits, temps, top_ks, top_ps):
    """Temperature-scale + per-row dynamic top-k + top-p mask.

    ``logits [N, V] f32``; ``temps [N]`` (rows with 0 pass through at
    scale 1 — their output is unused, greedy lanes argmax raw logits);
    ``top_ks [N] int32`` (0 = off); ``top_ps [N]`` (1.0 = off).
    Filtered entries become ``-inf``; at least the max survives."""
    v = logits.shape[-1]
    scale = jnp.where(temps > 0, temps, 1.0)[:, None]
    scaled = logits / scale
    # dynamic per-row top-k: threshold at each row's own k-th value
    order = -jnp.sort(-scaled, axis=-1)                     # descending
    k = jnp.clip(top_ks, 0, v)
    kth = jnp.take_along_axis(
        order, jnp.clip(k - 1, 0, v - 1)[:, None], axis=-1)
    scaled = jnp.where((k > 0)[:, None] & (scaled < kth),
                       -jnp.inf, scaled)
    # top-p over the top-k-filtered distribution
    order = -jnp.sort(-scaled, axis=-1)
    probs = jax.nn.softmax(order, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cut_idx = jnp.minimum(jnp.sum(cum < top_ps[:, None], axis=-1,
                                  keepdims=True), v - 1)
    cutoff = jnp.take_along_axis(order, cut_idx, axis=-1)
    scaled = jnp.where((top_ps < 1.0)[:, None] & (scaled < cutoff),
                       -jnp.inf, scaled)
    return scaled


def filtered_probs(logits, temps, top_ks, top_ps):
    """Softmax of :func:`filter_logits` — the per-row proposal /
    verification distribution (filtered entries have probability 0)."""
    return jax.nn.softmax(filter_logits(logits, temps, top_ks, top_ps),
                          axis=-1)


def sample_tokens(logits, temps, top_ks, top_ps, keys):
    """One token per row: categorical over the filtered distribution for
    ``temps > 0`` lanes, raw argmax for greedy lanes.  ``keys`` are the
    per-row PER-TOKEN keys (already folded with the token counter)."""
    filt = filter_logits(logits, temps, top_ks, top_ps)
    sampled = jax.vmap(jax.random.categorical)(
        jnp.asarray(keys).astype(jnp.uint32), filt)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


@jax.jit
def sample_at(logits, temps, top_ks, top_ps, keys, counters):
    """Sample row tokens at explicit counters: the exact program both
    ``generate()`` and the engine's first-token path run, so a request's
    i-th token is bitwise reproducible across the two front ends."""
    return sample_tokens(logits, temps, top_ks, top_ps,
                         fold_keys(keys, counters))


# ---------------------------------------------------------------------------
# compiled step
# ---------------------------------------------------------------------------

def make_sampled_decode_step(model, fused=None, kv_cache_dtype=None):
    """Paged decode with the sampling transform fused into the program:
    step(tok[S, 1] int32, pools [(k, v)] per layer, block_tables
    [S, max_blocks] int32, lengths[S] int32, temps[S] f32, top_ks[S]
    int32, top_ps[S] f32, keys[S, 2] uint32, counters[S] int32) ->
    (next_tok[S] int32, new_pools).

    Identical forward pass to ``make_paged_decode_step``; the only
    addition is the on-device fold + filter + categorical on the last
    logits, so only the chosen token ids sync back (a [S] int32 instead
    of the greedy step's [S, V] logits).  All per-slot sampling state
    rides in fixed-shape device arrays — zero retraces, zero host
    round-trips in the token loop (H106).  Cached on the model keyed by
    a weights fingerprint, like every other step builder."""
    from ..kernels.fusion import resolve_serving_fusion, serving_fusion
    from ..kernels.kv_quant import resolve_kv_cache_dtype
    from ..models.generation import (_kv_dtype_suffix, _unwrap_paged,
                                     _wrap_paged)

    fused = resolve_serving_fusion(fused)
    kv_dtype = resolve_kv_cache_dtype(kv_cache_dtype)
    attr = ("_sampled_decode_step_fused" if fused
            else "_sampled_decode_step") + _kv_dtype_suffix(kv_dtype)
    step = getattr(model, attr, None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, attr + "_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from ..core.dispatch import no_grad_ctx

    kind = "sampled_decode" + _kv_dtype_suffix(kv_dtype)

    @jax.jit
    @functools.partial(register_decode_step, kind=kind)
    def step(tok, pools, block_tables, lengths, temps, top_ks, top_ps,
             keys, counters):
        with no_grad_ctx(), serving_fusion(fused):
            wrapped = _wrap_paged(pools, block_tables, kv_dtype)
            logits, new_caches = model(Tensor(tok), caches=wrapped,
                                       position_offset=lengths)
            last = logits._value[:, -1].astype(jnp.float32)
            toks = sample_tokens(last, temps, top_ks, top_ps,
                                 fold_keys(keys, counters))
            return toks, _unwrap_paged(new_caches, kv_dtype)

    setattr(model, attr, step)
    setattr(model, attr + "_fp", fp)
    return step
