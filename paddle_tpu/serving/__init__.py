"""paddle_tpu.serving — continuous-batching LLM inference.

The ROADMAP's north star serves "heavy traffic from millions of users";
this package is the serving half of that claim.  It turns the one-shot
``models.generation.generate()`` loop into an engine that admits and
retires requests at EVERY decode iteration (Orca's iteration-level
scheduling) over a block-pool KV cache with free-list allocation and
preemption (vLLM's paged KV cache) — see PAPERS.md for both.  Because
the decode step's shapes are fixed by the engine config, the whole hot
loop is ONE compiled XLA program that never retraces: the TPU-native
serving property the rest of the framework is built around.

Layout:

- :mod:`engine`    — the continuous-batching :class:`Engine`
- :mod:`cache`     — :class:`BlockKVPool`, the paged cache memory manager
- :mod:`scheduler` — FCFS+fairness policy, admission control, preemption
- :mod:`metrics`   — TTFT/TPOT/queue-time counters + engine gauges
- :mod:`endpoint`  — Predictor-shaped :class:`Endpoint` front door
- :mod:`overload`  — load shedding, degradation ladder, step watchdog
- :mod:`router`    — :class:`Router`, prefix/load-aware fleet placement
- :mod:`replay`    — multi-tenant trace replay bench for the router
- :mod:`sampling`  — seeded temperature/top-k/top-p (:class:`SamplingParams`)
- :mod:`speculative` — draft-propose/target-verify decoding
- :mod:`stream`    — SSE framing over ``submit(on_token=...)``

Quick start::

    from paddle_tpu.serving import Engine, ServingConfig
    eng = Engine(model, ServingConfig(max_batch_size=8, block_size=16,
                                      num_blocks=128))
    req = eng.submit(prompt_ids, max_new_tokens=64, eos_token_id=2)
    eng.run_until_complete()
    tokens = req.output_ids()
    print(eng.stats())
"""
from __future__ import annotations

from .cache import BlockKVPool, PoolExhausted
from .endpoint import Endpoint
from .engine import Engine, ServingConfig
from .metrics import RequestTimeline, ServingMetrics
from .overload import (DEGRADED, FAILED, LADDER_LEVELS, SERVING,
                       EngineQuarantined, OverloadController)
from .replay import (Arrival, Tenant, build_trace, default_tenants,
                     replay_trace)
from .router import ROUTER_POLICIES, Router, RouterMetrics
from .sampling import SamplingParams
from .scheduler import (FINISHED, PREEMPTED, PREFILLING, QUEUED, RUNNING,
                        AdmissionError, QueueFull, Request, Scheduler)
from .speculative import SpeculativeConfig
from .stream import sse_event, sse_stream, stream_events

__all__ = [
    "Engine",
    "ServingConfig",
    "Endpoint",
    "BlockKVPool",
    "PoolExhausted",
    "Scheduler",
    "Request",
    "AdmissionError",
    "QueueFull",
    "ServingMetrics",
    "RequestTimeline",
    "OverloadController",
    "EngineQuarantined",
    "Router",
    "RouterMetrics",
    "ROUTER_POLICIES",
    "Tenant",
    "Arrival",
    "default_tenants",
    "build_trace",
    "replay_trace",
    "SamplingParams",
    "SpeculativeConfig",
    "sse_event",
    "sse_stream",
    "stream_events",
    "LADDER_LEVELS",
    "SERVING",
    "DEGRADED",
    "FAILED",
    "QUEUED",
    "PREFILLING",
    "RUNNING",
    "PREEMPTED",
    "FINISHED",
]
