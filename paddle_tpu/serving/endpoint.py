"""``serving.Endpoint`` — the Predictor-shaped front door to the
continuous-batching engine (reference: paddle_inference's
AnalysisPredictor run loop; see paddle_tpu/inference/__init__.py).

Two usage styles:

- Predictor parity: ``get_input_handle("input_0").copy_from_cpu(ids)``
  → ``run()`` → ``get_output_handle("output_0").copy_to_cpu()`` — one
  rectangular batch in, EOS-padded rectangular batch out, so code
  written against :class:`paddle_tpu.inference.Predictor` ports over.
- Streaming: ``submit()`` / ``poll()`` / ``drain()`` for callers that
  want requests admitted and retired at token granularity.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .engine import Engine, ServingConfig
from .scheduler import FINISHED, Request


class Endpoint:
    """``model`` may be a bare causal LM (an :class:`Engine` is built
    from it with ``config``), an already-constructed :class:`Engine`, or
    a :class:`~paddle_tpu.serving.router.Router` fleet — the router is
    engine-shaped (same submit/step/run_until_complete/health surface),
    so everything below works unchanged and ``health()`` reports
    aggregate FLEET health."""

    def __init__(self, model, config: Optional[ServingConfig] = None,
                 **generate_defaults):
        from .router import Router

        if isinstance(model, (Engine, Router)):
            if config is not None:
                raise ValueError(
                    "pass ServingConfig when Endpoint builds the engine "
                    "from a model; a prebuilt Engine/Router already "
                    "carries its config")
            self.engine = model
        else:
            self.engine = Engine(model, config)
        self._defaults = generate_defaults
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}

    # ------------------------------------------------- Predictor parity
    def get_input_names(self) -> List[str]:
        return ["input_0"]

    def get_output_names(self) -> List[str]:
        return ["output_0"]

    def get_input_handle(self, name: str) -> "_Handle":
        return _Handle(self._inputs, name)

    def get_output_handle(self, name: str) -> "_Handle":
        return _Handle(self._outputs, name)

    def run(self, prompts=None, **generate_kwargs) -> List[np.ndarray]:
        """Serve a batch: list/array of prompts (or the ``input_0``
        handle), continuous batching under the hood, outputs in submit
        order.  ``output_0`` holds an EOS-padded rectangular [B, T]
        array for handle-style callers; the return value keeps exact
        per-request lengths."""
        if prompts is None:
            prompts = self._inputs.get("input_0")
            if prompts is None:
                raise ValueError("no prompts: pass run(prompts) or "
                                 "copy_from_cpu into input_0")
        kwargs = {**self._defaults, **generate_kwargs}
        outs = self.engine.generate(list(np.asarray(p).reshape(-1)
                                         for p in prompts), **kwargs)
        pad = kwargs.get("eos_token_id") or 0
        width = max(o.size for o in outs)
        rect = np.full((len(outs), width), pad, np.int32)
        for i, o in enumerate(outs):
            rect[i, :o.size] = o
        self._outputs["output_0"] = rect
        return outs

    # --------------------------------------------------------- streaming
    def submit(self, prompt, **kwargs) -> Request:
        return self.engine.submit(prompt, **{**self._defaults, **kwargs})

    def poll(self) -> bool:
        """One engine iteration; True while work remains."""
        return self.engine.step()

    def drain(self) -> Dict[str, Request]:
        return self.engine.run_until_complete()

    def stream(self, prompt, **kwargs):
        """SSE response for ``prompt``: an iterator of ``data: <json>``
        frames (one per token, then a summary event and ``[DONE]``) —
        see :mod:`paddle_tpu.serving.stream`.  The engine keeps serving
        other in-flight requests while the caller drains."""
        from .stream import sse_stream
        return sse_stream(self, prompt, **{**self._defaults, **kwargs})

    def result(self, req: Request) -> Optional[np.ndarray]:
        return req.output_ids() if req.state == FINISHED else None

    def metrics(self) -> dict:
        return self.engine.stats()

    def health(self) -> dict:
        """Engine health snapshot (``Engine.health()``): the
        serving/degraded/failed state, degradation-ladder level and
        watchdog totals a load balancer needs for readiness checks."""
        return self.engine.health()


class _Handle:
    """ZeroCopyTensor-shaped view over an Endpoint io dict."""

    def __init__(self, store: dict, name: str):
        self._store = store
        self.name = name

    def reshape(self, shape):
        pass

    def copy_from_cpu(self, data):
        self._store[self.name] = np.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._store[self.name])

    @property
    def shape(self):
        a = self._store.get(self.name)
        return list(a.shape) if a is not None else None
