# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Optimizer base + concrete optimizers.

Capability analog of the reference optimizer stack
(/root/reference/python/paddle/optimizer/optimizer.py: _create_accumulators,
_append_optimize_op; the reference implements each update as a CUDA op in
paddle/fluid/operators/optimizers/).  Here each update rule is ONE jitted
functional XLA computation per (shape, dtype) — donated buffers, fused
multiply-adds, no per-element Python.  Under jit.to_static the same rules
inline into the whole-step program.
"""
from __future__ import annotations

import functools

import numpy as np
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import no_grad_ctx
from ..core.tensor import Parameter, Tensor


class LRSchedulerRef:
    pass


def _get_lr_value(lr):
    if hasattr(lr, "traced"):  # jit.to_static passes the LR as a traced scalar
        return lr.traced
    from .lr import LRScheduler

    if isinstance(lr, LRScheduler):
        return lr()
    return float(lr)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._name = name
        self._multi_precision = multi_precision
        if isinstance(weight_decay, (float, int)):
            self._weight_decay = float(weight_decay)
        elif weight_decay is None:
            self._weight_decay = 0.0
        else:  # L2Decay-like object with a coeff
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay, "coeff", 0.0)))
        # per-parameter accumulator slots: name -> {id(param): jnp array}
        self._accumulators: Dict[str, Dict[int, jnp.ndarray]] = {}
        self._step_count = 0
        # step as a device scalar so compiled training steps don't bake it
        # (jit.to_static captures it as program state)
        self._global_state: Dict[str, jnp.ndarray] = {
            "step": jnp.zeros((), jnp.int32)}

    # ------------------------------------------------------------ accumulators
    def _add_accumulator(self, name, param, fill=0.0, dtype=None, shape=None):
        store = self._accumulators.setdefault(name, {})
        if id(param) not in store:
            shp = tuple(shape) if shape is not None else tuple(param.shape)
            dt = dtype or (jnp.float32 if self._multi_precision
                           else param._value.dtype)
            store[id(param)] = jnp.full(shp, fill, dt)
        return store[id(param)]

    def _get_accumulator(self, name, param):
        return self._accumulators[name][id(param)]

    def _set_accumulator(self, name, param, value):
        self._accumulators[name][id(param)] = value

    # ---------------------------------------------------------------- lr
    def get_lr(self) -> float:
        return _get_lr_value(self._learning_rate)

    def set_lr(self, value: float):
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        from .lr import LRScheduler

        return self._learning_rate if isinstance(self._learning_rate,
                                                 LRScheduler) else None

    # ---------------------------------------------------------------- step
    def _collect_params_grads(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("Optimizer created without parameters")
        out = []
        for p in params:
            if isinstance(p, dict):
                # parameter group dict {'params': [...], 'learning_rate'/'weight_decay': ...}
                for q in p["params"]:
                    out.append((q, q.grad, p))
            else:
                out.append((p, p.grad, None))
        return out

    @jax.named_scope("optimizer_step")
    def step(self):
        with no_grad_ctx():
            params_grads = [(p, g) for p, g, _grp in self._collect_params_grads()
                            if g is not None and getattr(p, "trainable", True)]
            if self._grad_clip is not None:
                params_grads = self._grad_clip(params_grads)
            lr = self.get_lr()
            for p, g in params_grads:
                self._update_param(p, g._value if isinstance(g, Tensor) else g,
                                   lr)
        self._step_count += 1
        self._global_state["step"] = self._global_state["step"] + 1

    def _update_param(self, param, grad, lr):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=False):
        for p, _, _ in self._collect_params_grads():
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import graph as _G

        if isinstance(loss, _G.Variable):
            return self._static_minimize(loss, parameters, no_grad_set)
        loss.backward()
        self.step()
        return None, None

    # ------------------------------------------------------- static graph
    def _static_minimize(self, loss, parameters=None, no_grad_set=None):
        """Record backward + update ops into the static Program (the
        reference's Optimizer.minimize in static mode appends grad ops via
        append_backward then _append_optimize_op per param)."""
        from ..static import graph as _G

        params = parameters or self._parameter_list
        if params:  # flatten parameter-group dicts
            flat_params = []
            for p in params:
                if isinstance(p, dict):
                    flat_params.extend(p["params"])
                else:
                    flat_params.append(p)
            params = flat_params
        params_grads = _G.append_backward(loss, params, no_grad_set)

        if self._grad_clip is not None:
            gvars = [g for _, g in params_grads]

            def clip_fn(*gvals):
                pg = [(p, Tensor(v))
                      for (p, _), v in zip(params_grads, gvals)]
                return tuple(t._value for _, t in self._grad_clip(pg))

            from ..core.dispatch import apply

            clipped = apply("grad_clip", clip_fn, *gvars,
                            _differentiable=False)
            params_grads = [(p, g) for (p, _), g in
                            zip(params_grads, clipped)]

        for p, g_var in params_grads:
            self._record_update_op(p, g_var)
        self._record_step_op(loss.block)
        return [], params_grads

    def _probe_accumulators(self, p):
        """Discover this rule's accumulator slots (names + init arrays) by
        running the update once on a zero-grad probe with decay disabled."""
        # fresh zero buffer: update rules donate their param argument, so the
        # probe must not share p's buffer
        probe = Parameter(jnp.zeros_like(p._value),
                          name=getattr(p, "name", None))
        saved_wd = self._weight_decay
        self._weight_decay = 0.0
        try:
            self._update_param(probe, jnp.zeros_like(p._value), 0.0)
        finally:
            self._weight_decay = saved_wd
        names, inits = [], []
        for acc_name in sorted(self._accumulators):
            store = self._accumulators[acc_name]
            if id(probe) in store:
                names.append(acc_name)
                inits.append(store.pop(id(probe)))
        return names, inits

    def _record_update_op(self, p, g_var):
        from ..static import graph as _G

        blk = g_var.block
        acc_names, acc_inits = self._probe_accumulators(p)
        for acc_name, init in zip(acc_names, acc_inits):
            store = self._accumulators.setdefault(acc_name, {})
            if id(p) not in store:
                store[id(p)] = init
        slots = [Tensor(self._accumulators[n][id(p)]) for n in acc_names]
        n_acc = len(acc_names)
        opt = self

        def opt_fn(p_val, g_val, *rest):
            acc_vals, lr_val, step_val = rest[:n_acc], rest[n_acc], rest[n_acc + 1]
            tmp = Parameter(p_val, name=getattr(p, "name", None))
            saved_step = opt._global_state["step"]
            opt._global_state["step"] = step_val - 1  # rules use step+1
            for acc_name, v in zip(acc_names, acc_vals):
                opt._accumulators[acc_name][id(tmp)] = v
            try:
                opt._update_param(tmp, g_val, lr_val)
                new_accs = tuple(opt._accumulators[acc_name].pop(id(tmp))
                                 for acc_name in acc_names)
            finally:
                opt._global_state["step"] = saved_step
                for acc_name in acc_names:
                    opt._accumulators[acc_name].pop(id(tmp), None)
            return (tmp._value,) + new_accs

        def p_setter(v, _p=p):
            _p._value = v

        def make_acc_setter(store, pid, slot):
            def set_(v):
                slot._value = v
                store[pid] = v
            return set_

        inputs = ([("const", p), ("var", g_var)]
                  + [("const", s) for s in slots]
                  + [("dyn", lambda: jnp.float32(opt.get_lr())),
                     ("dyn", lambda: opt._global_state["step"] + 1)])
        out_avals = [jax.ShapeDtypeStruct(tuple(p._value.shape),
                                          p._value.dtype)]
        out_avals += [jax.ShapeDtypeStruct(tuple(s._value.shape),
                                           s._value.dtype) for s in slots]
        outputs = [blk.create_var(a, name=blk.program._unique_name(
            f"{type(self).__name__.lower()}_out")) for a in out_avals]
        writeback = [(0, p_setter)]
        for i, (acc_name, slot) in enumerate(zip(acc_names, slots)):
            writeback.append(
                (1 + i, make_acc_setter(self._accumulators[acc_name],
                                        id(p), slot)))
        blk.append_op(_G.OpDesc(
            f"{type(self).__name__.lower()}_update", opt_fn, {}, inputs,
            None, outputs, single=False, writeback=writeback))

    def _record_step_op(self, blk):
        from ..static import graph as _G

        opt = self

        def step_fn(step_next):
            return step_next

        def step_setter(v):
            opt._global_state["step"] = v
            opt._step_count += 1

        out = blk.create_var(jax.ShapeDtypeStruct((), jnp.int32),
                             name=blk.program._unique_name("global_step"))
        blk.append_op(_G.OpDesc(
            "increment_step", step_fn, {},
            [("dyn", lambda: opt._global_state["step"] + 1)],
            None, [out], single=True, writeback=[(0, step_setter)]))

    # ---------------------------------------------------------------- state
    def state_dict(self):
        state = {}
        params = {id(p): name_i for name_i, (p, _, _) in
                  enumerate(self._collect_params_grads())}
        for acc_name, store in self._accumulators.items():
            for pid, arr in store.items():
                # SNAPSHOT semantics: jnp.array copies into a fresh
                # buffer — the live slot array gets DONATED by the next
                # compiled step, and a reference to it would turn into
                # "Array has been deleted" at save time
                state[f"{acc_name}_{params.get(pid, pid)}"] = Tensor(
                    jnp.array(arr))
        state["@step"] = self._step_count
        # the DEVICE step counter drives bias correction (adam rules use
        # _global_state['step'] + 1); restoring only _step_count would
        # silently restart the correction schedule — the resume
        # trajectory then diverges from the uninterrupted one
        state["@global_step"] = int(np.asarray(self._global_state["step"]))
        if self._lr_scheduler is not None:
            state["LR_Scheduler"] = self._lr_scheduler.state_dict()
        return state

    def set_state_dict(self, state):
        self._step_count = int(state.get("@step", 0))
        self._global_state["step"] = jnp.asarray(
            int(state.get("@global_step", state.get("@step", 0))),
            jnp.int32)
        params = {name_i: p for name_i, (p, _, _) in
                  enumerate(self._collect_params_grads())}
        for key, value in state.items():
            if key in ("@step", "@global_step"):
                continue
            if key == "LR_Scheduler" and self._lr_scheduler is not None:
                self._lr_scheduler.set_state_dict(value)
                continue
            name, _, idx = key.rpartition("_")
            try:
                p = params[int(idx)]
            except (ValueError, KeyError):
                continue
            # jnp.array COPIES: aliasing the checkpoint's buffer into a
            # live slot would let the next compiled step donate (delete)
            # it out from under the caller's state dict
            arr = jnp.array(value._value if isinstance(value, Tensor)
                            else value)
            self._accumulators.setdefault(name, {})[id(p)] = arr


# --------------------------------------------------------------------- rules
# Jitted update rules (module-level so jax caches one executable per shape).

@functools.partial(jax.jit, donate_argnums=(0,))
def _sgd_rule(p, g, lr, wd):
    g = g + wd * p
    return (p - lr * g.astype(p.dtype)).astype(p.dtype)


@functools.partial(jax.jit, donate_argnums=(0, 1), static_argnames=("use_nesterov",))
def _momentum_rule(p, vel, g, lr, mu, wd, use_nesterov=False):
    g = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
    vel = mu * vel + g
    if use_nesterov:
        upd = g + mu * vel
    else:
        upd = vel
    return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), vel


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _adam_rule(p, m, v, g, lr, beta1, beta2, eps, step, wd_l2):
    g = g.astype(jnp.float32)
    if wd_l2 is not None:
        g = g + wd_l2 * p.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** step)
    vhat = v / (1 - beta2 ** step)
    new_p = p.astype(jnp.float32) - lr * mhat / (jnp.sqrt(vhat) + eps)
    return new_p.astype(p.dtype), m, v


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _adamw_rule(p, m, v, g, lr, beta1, beta2, eps, step, wd):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    pf = pf * (1.0 - lr * wd)  # decoupled decay
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** step)
    vhat = v / (1 - beta2 ** step)
    new_p = pf - lr * mhat / (jnp.sqrt(vhat) + eps)
    return new_p.astype(p.dtype), m, v


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _adagrad_rule(p, moment, g, lr, eps, wd):
    g = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
    moment = moment + jnp.square(g)
    new_p = p.astype(jnp.float32) - lr * g / (jnp.sqrt(moment) + eps)
    return new_p.astype(p.dtype), moment


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _adadelta_rule(p, avg_sq_grad, avg_sq_update, g, lr, rho, eps, wd):
    # reference adadelta_kernel_impl.h:54: param += update with NO
    # learning-rate factor (classic Adadelta; the phi kernel takes no LR
    # input, so paddle's learning_rate arg is inert) — multiplying by
    # the default lr=0.001 made updates 1000x too small
    del lr
    g = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
    avg_sq_grad = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = jnp.sqrt(avg_sq_update + eps) / jnp.sqrt(avg_sq_grad + eps) * g
    avg_sq_update = rho * avg_sq_update + (1 - rho) * jnp.square(update)
    return (p.astype(jnp.float32) - update).astype(p.dtype), \
        avg_sq_grad, avg_sq_update


@functools.partial(jax.jit, donate_argnums=(0, 1, 2), static_argnames=("centered",))
def _rmsprop_rule(p, mean_sq, mom, g, lr, rho, eps, momentum, wd, mean_g,
                  centered=False):
    g = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
    mean_sq = rho * mean_sq + (1 - rho) * jnp.square(g)
    if centered:
        mean_g = rho * mean_g + (1 - rho) * g
        denom = jnp.sqrt(mean_sq - jnp.square(mean_g) + eps)
    else:
        denom = jnp.sqrt(mean_sq + eps)
    mom = momentum * mom + lr * g / denom
    return (p.astype(jnp.float32) - mom).astype(p.dtype), mean_sq, mom, mean_g


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _adamax_rule(p, m, u, g, lr, beta1, beta2, eps, step, wd):
    g = g.astype(jnp.float32) + wd * p.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    # reference adamax_kernel_impl.h:60: eps rides INSIDE the max
    # (u = max(|g|, beta2*u + eps)), and the denominator gets u alone
    u = jnp.maximum(jnp.abs(g), beta2 * u + eps)
    new_p = p.astype(jnp.float32) - lr / (1 - beta1 ** step) * m / u
    return new_p.astype(p.dtype), m, u


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _lamb_rule(p, m, v, g, lr, beta1, beta2, eps, step, wd):
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** step)
    vhat = v / (1 - beta2 ** step)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * pf
    p_norm = jnp.linalg.norm(pf)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    return (pf - lr * trust * r).astype(p.dtype), m, v


# ------------------------------------------------------------------ classes
class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _update_param(self, p, g, lr):
        p._value = _sgd_rule(p._value, g, lr, self._weight_decay)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update_param(self, p, g, lr):
        vel = self._add_accumulator("velocity", p, dtype=jnp.float32)
        p._value, vel = _momentum_rule(p._value, vel, g, lr, self._momentum,
                                       self._weight_decay,
                                       use_nesterov=self._use_nesterov)
        self._set_accumulator("velocity", p, vel)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_param(self, p, g, lr):
        m = self._add_accumulator("moment1", p, dtype=jnp.float32)
        v = self._add_accumulator("moment2", p, dtype=jnp.float32)
        wd = self._weight_decay if self._weight_decay else None
        p._value, m, v = _adam_rule(p._value, m, v, g, lr, self._beta1,
                                    self._beta2, self._epsilon,
                                    self._global_state["step"] + 1, wd)
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)


class AdamW(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._apply_decay_param_fun = apply_decay_param_fun

    def _update_param(self, p, g, lr):
        m = self._add_accumulator("moment1", p, dtype=jnp.float32)
        v = self._add_accumulator("moment2", p, dtype=jnp.float32)
        wd = self._weight_decay
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(getattr(p, "name", None) or ""):
            wd = 0.0
        p._value, m, v = _adamw_rule(p._value, m, v, g, lr, self._beta1,
                                     self._beta2, self._epsilon,
                                     self._global_state["step"] + 1, wd)
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _update_param(self, p, g, lr):
        mom = self._add_accumulator("moment", p, fill=self._initial,
                                    dtype=jnp.float32)
        p._value, mom = _adagrad_rule(p._value, mom, g, lr, self._epsilon,
                                      self._weight_decay)
        self._set_accumulator("moment", p, mom)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon, self._rho = epsilon, rho

    def _update_param(self, p, g, lr):
        asg = self._add_accumulator("avg_squared_grad", p, dtype=jnp.float32)
        asu = self._add_accumulator("avg_squared_update", p, dtype=jnp.float32)
        p._value, asg, asu = _adadelta_rule(p._value, asg, asu, g, lr,
                                            self._rho, self._epsilon,
                                            self._weight_decay)
        self._set_accumulator("avg_squared_grad", p, asg)
        self._set_accumulator("avg_squared_update", p, asu)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _update_param(self, p, g, lr):
        ms = self._add_accumulator("mean_square", p, dtype=jnp.float32)
        mom = self._add_accumulator("momentum", p, dtype=jnp.float32)
        mg = self._add_accumulator("mean_grad", p, dtype=jnp.float32)
        p._value, ms, mom, mg = _rmsprop_rule(
            p._value, ms, mom, g, lr, self._rho, self._epsilon, self._momentum,
            self._weight_decay, mg, centered=self._centered)
        self._set_accumulator("mean_square", p, ms)
        self._set_accumulator("momentum", p, mom)
        self._set_accumulator("mean_grad", p, mg)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_param(self, p, g, lr):
        m = self._add_accumulator("moment", p, dtype=jnp.float32)
        u = self._add_accumulator("inf_norm", p, dtype=jnp.float32)
        p._value, m, u = _adamax_rule(p._value, m, u, g, lr, self._beta1,
                                      self._beta2, self._epsilon,
                                      self._global_state["step"] + 1,
                                      self._weight_decay)
        self._set_accumulator("moment", p, m)
        self._set_accumulator("inf_norm", p, u)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, g, lr):
        m = self._add_accumulator("moment1", p, dtype=jnp.float32)
        v = self._add_accumulator("moment2", p, dtype=jnp.float32)
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        p._value, m, v = _lamb_rule(p._value, m, v, g, lr, self._beta1,
                                    self._beta2, self._epsilon,
                                    self._global_state["step"] + 1, wd)
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _lars_rule(p, vel, g, lr, mu, lars_coeff, lars_wd, eps):
    """Layer-wise adaptive rate scaling (reference:
    paddle/fluid/operators/optimizers/lars_momentum_op.cc)."""
    pf = p.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    p_norm = jnp.linalg.norm(pf)
    g_norm = jnp.linalg.norm(gf)
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * lars_coeff * p_norm / (g_norm + lars_wd * p_norm + eps),
        lr)
    vel = mu * vel + local_lr * (gf + lars_wd * pf)
    return (pf - vel).astype(p.dtype), vel


class LarsMomentum(Optimizer):
    """LARS (reference: python/paddle/fluid/optimizer.py
    LarsMomentumOptimizer; fleet meta_optimizers/lars_optimizer.py)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=0.0, multi_precision=False, name=None,
                 exclude_from_weight_decay=None, **kwargs):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = tuple(exclude_from_weight_decay or ())

    def _update_param(self, p, g, lr):
        vel = self._add_accumulator("velocity", p, dtype=jnp.float32)
        wd = self._lars_weight_decay
        pname = getattr(p, "name", "") or ""
        if any(tag in pname for tag in self._exclude):
            wd = 0.0
        p._value, vel = _lars_rule(p._value, vel, g, lr, self._momentum,
                                   self._lars_coeff, wd, self._epsilon)
        self._set_accumulator("velocity", p, vel)
