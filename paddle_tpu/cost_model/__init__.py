# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.cost_model (reference: python/paddle/cost_model/cost_model.py —
CostModel: profile a static program for per-op costs, plus a static
op-benchmark table lookup).

TPU-native redesign: the reference ships a pre-measured GPU JSON table
(static_op_benchmark.json) and a C++ profiler hook.  Neither fits here —
op kernels don't exist as schedulable units after XLA fusion.  Instead:

- ``profile_measure`` runs the program under the Executor and returns
  measured wall time plus XLA's own cost analysis (flops / bytes
  accessed) for the compiled executable — the numbers the XLA scheduler
  itself plans with.
- ``static_cost_data`` / ``get_static_op_time`` serve an ANALYTIC table:
  per-op flop/byte estimates from the op schema, convertible to seconds
  via the measured device peak.  No baked-in foreign-hardware numbers.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._static_cost_data: Optional[List[Dict]] = None
        self._measured: Dict[str, float] = {}

    # -- reference parity: the toy program used by its example/tests ------
    def build_program(self):
        import paddle_tpu as paddle
        from paddle_tpu import static

        paddle.enable_static()
        main_program = static.Program()
        startup_program = static.Program()
        with static.program_guard(main_program=main_program,
                                  startup_program=startup_program):
            data = static.data(name="X", shape=[None, 1], dtype="float32")
            hidden = static.nn.fc(data, 10)
            loss = paddle.mean(hidden)
            paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return startup_program, main_program

    def profile_measure(self, startup_program, main_program, device="tpu",
                        fetch_cost_list=("time",), feed=None, repeat=3):
        """Execute the program and measure.  Returns a dict with:
        - "time": median wall ms per run
        - "op_count": ops in the main block
        - "cost_analysis": XLA flops/bytes for the jitted step when the
          backend exposes them (flops, bytes accessed, utilization keys)
        """
        import paddle_tpu as paddle
        from paddle_tpu import static

        paddle.enable_static()
        exe = static.Executor()
        exe.run(startup_program)
        if feed is None:
            feed = {"X": np.random.random((10, 1)).astype("float32")}
        exe.run(main_program, feed=feed)  # compile + warm
        times = []
        for _ in range(repeat):
            t0 = time.perf_counter()
            exe.run(main_program, feed=feed)
            times.append((time.perf_counter() - t0) * 1e3)
        result = {"time": float(np.median(times)),
                  "op_count": len(main_program.global_block().ops)}
        try:
            import jax

            # cost analysis of an equivalent jitted add: backend probe that
            # the API exists; per-program analysis rides the Executor cache
            compiled = getattr(exe, "_last_compiled", None)
            if compiled is not None and hasattr(compiled, "cost_analysis"):
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                result["cost_analysis"] = {
                    k: float(v) for k, v in dict(ca).items()
                    if isinstance(v, (int, float))}
        except Exception:
            pass
        self._measured["__program__"] = result["time"]
        return result

    # -- analytic static table -------------------------------------------
    _ANALYTIC = {
        # op -> (flops per element-ish unit, note); matmul handled apart
        "relu": 1.0, "add": 1.0, "elementwise_add": 1.0, "scale": 1.0,
        "softmax": 5.0, "layer_norm": 8.0, "mean": 1.0, "sum": 1.0,
    }

    def static_cost_data(self):
        """The analytic per-op table (reference reads
        static_op_benchmark.json; that file is GPU-measured data we
        neither have nor want — entries here are derived)."""
        if self._static_cost_data is None:
            self._static_cost_data = [
                {"op": name, "config": "dtype=float32",
                 "flops_per_element": fpe,
                 "paddle_gpu_time": None,     # reference-table field names
                 "paddle_gpu_time_backward": None}
                for name, fpe in sorted(self._ANALYTIC.items())]
        return self._static_cost_data

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        """Per-op cost entry.  Analytic flops/element converted to a time
        estimate only relative to the measured program when available —
        absolute per-op microseconds don't exist post-fusion on XLA."""
        if op_name is None:
            raise ValueError(
                "op_name should not be empty when you want to get static "
                "op time")
        for entry in self.static_cost_data():
            if entry["op"] == op_name and dtype in entry["config"]:
                scale = 1.0 if forward else 2.0  # bwd ~2x fwd flops
                return {"op": op_name, "forward": forward,
                        "flops_per_element": entry["flops_per_element"]
                        * scale}
        raise ValueError(f"no static cost entry for op {op_name!r}")
