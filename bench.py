"""Benchmark: Llama pretrain tokens/sec/chip on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no absolute numbers (BASELINE.md) — vs_baseline
reports achieved MFU (model flops utilization) as the comparable scalar.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # 603M-param Llama (hidden 2048 → 128-lane-aligned matmuls that
        # saturate the MXU).  Fits one v5e chip with the chunked fused
        # lm-head loss; measured MFU ~0.47 vs 0.22 for the old h1024 config.
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=10, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            dtype="bfloat16")
        batch, seq, steps, warmup = 8, 2048, 20, 5
    else:  # smoke path for CPU dev runs
        cfg = LlamaConfig.tiny()
        batch, seq, steps, warmup = 2, 64, 5, 2

    model = LlamaForCausalLM(cfg)
    opt = AdamW(1e-4, parameters=model.parameters())

    @jit.to_static
    def train_step(tokens):
        loss, _ = model(tokens, labels=tokens)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.RandomState(0)
    tokens = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    for _ in range(warmup):
        loss = train_step(tokens)
    np.asarray(loss.numpy())  # hard sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = train_step(tokens)
        loss._value.block_until_ready()  # per-step sync: robust timing on
        # remote-tunnel backends where a tail sync can miss the chain
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt

    # params (embedding counted once) for 6N flops/token + attention term
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = (6.0 * n_params
                       + 12.0 * cfg.num_hidden_layers * cfg.hidden_size * seq)
    achieved_flops = tokens_per_sec * flops_per_token
    # v5e bf16 peak ~197 TFLOP/s; CPU smoke has no meaningful peak
    peak = 197e12 if on_tpu else None
    mfu = achieved_flops / peak if peak else None

    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 4) if mfu is not None else None,
    }))
    print(f"# model={n_params/1e6:.1f}M params, batch={batch}, seq={seq}, "
          f"steps={steps}, step_time={dt/steps*1000:.1f}ms, "
          f"loss={float(np.asarray(loss.numpy())):.4f}, "
          f"backend={jax.default_backend()}", file=sys.stderr)


if __name__ == "__main__":
    main()
