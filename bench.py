"""Benchmark: Llama pretrain tokens/sec/chip on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no absolute numbers (BASELINE.md) — vs_baseline
reports achieved MFU (model flops utilization) as the comparable scalar.

Hardened (round 2): backend init is retried with backoff (a held/ busy TPU
surfaces as UNAVAILABLE at first op execution), peak FLOPs are derived from
the detected chip kind instead of a hard-coded v5e number, and every failure
path still emits the JSON line (with an "error" field) and exits 0 — a bench
that produces no number is a failed perf gate
(reference: tools/check_op_benchmark_result.py:106 semantics).
"""
from __future__ import annotations

import json
import sys
import time
import traceback

import numpy as np

# bf16 peak FLOP/s per chip by PJRT device_kind substring (public specs).
# Checked in order; first match wins.
_PEAK_FLOPS = (
    ("v6e", 918e12), ("v6 lite", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12), ("v5 lite", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def _expects_accelerator() -> bool:
    import os

    plats = os.environ.get("JAX_PLATFORMS", "")
    return bool(plats) and "cpu" not in plats.split(",")


# recorded by _init_backend; run_bench folds it into the emitted JSON so
# every artifact says WHICH backend produced the number and, on CPU
# fallback, why the accelerator was skipped (ROADMAP "bench backend
# probe is broken": five rounds of artifacts died in probe timeouts and
# carried no backend provenance at all)
_PROBE_RESULT = {"probed_backend": None, "probe_error": None,
                 "probe_attempts": 0}


def _init_backend(total_budget: float | None = None):
    """Return (devices, backend_name) via bounded subprocess probes.

    A TPU held by a stale process (or a racing tunnel) raises
    RuntimeError("... UNAVAILABLE ...") from the first devices() call.
    JAX caches backend-init state after the first in-process attempt (a
    failed TPU init leaves a CPU-only backend dict that later calls return
    silently), so the probe runs in a FRESH SUBPROCESS; jax is only
    imported here once the probe confirms the accelerator answers.
    Without the probe, a retry would "succeed" on CPU and the bench would
    report a smoke-path number as the real perf result.

    Every probe — including the FIRST — runs under a hard per-probe
    deadline (BENCH_PROBE_DEADLINE, default 60 s).  The previous
    adaptive scheme granted the first probe the whole remaining budget,
    so a hung 'axon' platform probe starved the entire 300 s budget and
    the CPU metric suite never ran (BENCH_r01–r05 all died this way).
    A probe that times out now costs one deadline, not the run: we fall
    back to CPU, record the probed backend and failure reason in
    ``_PROBE_RESULT`` (emitted in the JSON), and still produce the full
    per-subsystem metric suite.  Fast failures (clean UNAVAILABLE) are
    retried with backoff inside the total budget as before.
    """
    import os
    import subprocess

    if total_budget is None:
        total_budget = float(os.environ.get("BENCH_PROBE_BUDGET", 300.0))
    probe_deadline = float(os.environ.get("BENCH_PROBE_DEADLINE", 60.0))
    deadline = time.monotonic() + total_budget
    last_err = None
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 5.0:
            why = ("fast-fail probes exhausted the budget" if last_err
                   else "time budget exhausted")
            break
        attempt += 1
        _PROBE_RESULT["probe_attempts"] = attempt
        timeout = min(probe_deadline, remaining)
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); "
                 "print(jax.default_backend())"],
                capture_output=True, text=True,
                timeout=timeout,  # hard per-probe deadline, never the
                env=dict(os.environ))  # whole remaining budget
        except subprocess.TimeoutExpired as e:
            tail = ((e.stderr if isinstance(e.stderr, str) else
                     (e.stderr or b"").decode("utf-8", "replace"))
                    or "").strip()[-500:]
            last_err = (f"probe timed out after {timeout:.0f}s "
                        f"(per-probe deadline); probe stderr tail: "
                        f"{tail!r}")
            why = f"probe hung past its {probe_deadline:.0f}s deadline"
            print(f"# backend probe {attempt}: {last_err}", file=sys.stderr)
            break  # a hang is not transient: don't burn more deadlines
        probed = probe.stdout.strip().splitlines()[-1] if \
            probe.stdout.strip() else ""
        if probe.returncode == 0 and (
                probed != "cpu" or not _expects_accelerator()):
            import jax

            devices = jax.devices()
            backend = jax.default_backend()
            if backend == "cpu" and _expects_accelerator():
                # probe saw the accelerator but our init lost the race
                raise RuntimeError(
                    "accelerator probe succeeded but in-process init fell "
                    "back to cpu — TPU likely grabbed by another process")
            _PROBE_RESULT["probed_backend"] = backend
            _PROBE_RESULT["probe_error"] = None
            return devices, backend
        last_err = (f"probe exited rc={probe.returncode} backend="
                    f"{probed or 'none'}; probe stderr tail: "
                    f"{(probe.stderr or probe.stdout or '').strip()[-500:]!r}")
        wait = min(5.0 * attempt, max(0.0, deadline - time.monotonic()))
        print(f"# backend probe {attempt} failed fast: {last_err}; "
              f"retrying in {wait:.0f}s", file=sys.stderr)
        time.sleep(wait)
    if _expects_accelerator():
        # the accelerator never answered inside its deadline: fall back
        # to CPU so the metric suite still runs, and stamp the artifact
        # with the probed backend + failure reason (the fallback is
        # explicit provenance, never silent)
        _PROBE_RESULT["probed_backend"] = "cpu"
        _PROBE_RESULT["probe_error"] = (
            f"accelerator probe failed ({why}, budget "
            f"{total_budget:.0f}s, per-probe deadline "
            f"{probe_deadline:.0f}s): {last_err}")
        print(f"# falling back to cpu: {_PROBE_RESULT['probe_error']}",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        # a dead tunnel's PJRT plugin registration hangs at import when
        # this is set (same guard as tools/ci.sh)
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import jax

        return jax.devices("cpu"), "cpu"
    raise RuntimeError(
        f"backend init failed ({why}, budget {total_budget:.0f}s): "
        f"{last_err}")


def _emit(result: dict):
    # every artifact line carries backend provenance: which backend the
    # probe settled on and (on CPU fallback / init failure) why — the
    # gate must never read a fallback number as accelerator evidence
    if _PROBE_RESULT["probed_backend"] is not None:
        result.setdefault("probed_backend", _PROBE_RESULT["probed_backend"])
    if _PROBE_RESULT["probe_error"] is not None:
        result.setdefault("probe_error", _PROBE_RESULT["probe_error"])
    print(json.dumps(result))
    sys.stdout.flush()


def _n_chips() -> int:
    """Device count for per-chip normalization of serving headlines."""
    try:
        import jax

        return max(1, len(jax.devices()))
    except Exception:  # noqa: BLE001
        return 1


def _eager_overhead_us(n_ops: int = 1000):
    """Per-op eager-dispatch overhead: Tensor-path chained adds vs raw jnp
    (SURVEY §7 'eager-mode performance' hard part; the reference's hot
    loop is TraceOpImpl, SURVEY §3.1).  Returns (overhead_us_per_op,
    tensor_us_per_op, jnp_us_per_op)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle

    x_t = paddle.to_tensor(np.ones((64, 64), np.float32))
    x_j = jnp.ones((64, 64), jnp.float32)

    def chain_tensor(n):
        acc = x_t
        for _ in range(n):
            acc = acc + x_t
        acc._value.block_until_ready()

    def chain_jnp(n):
        acc = x_j
        for _ in range(n):
            acc = acc + x_j
        acc.block_until_ready()

    chain_tensor(50)  # warm caches
    chain_jnp(50)
    t0 = time.perf_counter()
    chain_tensor(n_ops)
    t_tensor = time.perf_counter() - t0
    t0 = time.perf_counter()
    chain_jnp(n_ops)
    t_jnp = time.perf_counter() - t0
    per_op = (t_tensor - t_jnp) / n_ops * 1e6
    return round(per_op, 3), round(t_tensor / n_ops * 1e6, 3), \
        round(t_jnp / n_ops * 1e6, 3)


def _moe_bench(on_tpu: bool):
    """Second BASELINE config (expert-parallel MoE proxy, single chip):
    tokens/s through a jitted fwd+bwd of an 8-expert top-2 MoE block
    (BASELINE.md config 4; reference MoE path python/paddle/incubate/
    distributed/models/moe/moe_layer.py)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.distributed.moe import MoELayer
    from paddle_tpu.optimizer import AdamW

    if on_tpu:
        d_model, d_hidden, experts = 1024, 4096, 8
        batch, seq, steps, warmup = 8, 512, 10, 3
    else:
        d_model, d_hidden, experts = 32, 64, 4
        batch, seq, steps, warmup = 2, 16, 25, 3
    moe = MoELayer(d_model=d_model, d_hidden=d_hidden, num_experts=experts,
                   top_k=2)
    opt = AdamW(1e-4, parameters=moe.parameters())

    @jit.to_static
    def step(x):
        out = moe(x)
        loss = (out * out).mean() + 0.01 * moe.aux_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, seq, d_model).astype(np.float32))
    for _ in range(warmup):
        loss = step(x)
    loss._value.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x)
        loss._value.block_until_ready()
    dt = time.perf_counter() - t0
    return round(batch * seq * steps / dt, 1)


def _unet_bench(on_tpu: bool):
    """Third BASELINE config (SDXL-UNet inference proxy, config 5):
    denoise-step latency (ms) of a jitted UNet2DConditionModel forward —
    the reference serves this through Paddle Inference's predictor
    (ppdiffusers + inference/api.cc); here the predictor path IS jit."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.models.unet import UNet2DConditionModel, UNetConfig

    if on_tpu:
        cfg = UNetConfig(dtype="bfloat16")  # SDXL channel plan
        B, HW, T = 1, 64, 77
    else:
        cfg = UNetConfig.tiny()
        B, HW, T = 1, 8, 4
    model = UNet2DConditionModel(cfg)
    model.eval()

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    lat = paddle.to_tensor(jnp.asarray(
        rng.randn(B, cfg.in_channels, HW, HW), dt))
    ts = paddle.to_tensor(np.asarray([500], np.int32))
    ctx = paddle.to_tensor(jnp.asarray(
        rng.randn(B, T, cfg.cross_attention_dim), dt))

    @jit.to_static
    def denoise(lat, ts, ctx):
        return model(lat, ts, ctx)

    steps, warmup = (10, 3) if on_tpu else (10, 2)
    for _ in range(warmup):
        out = denoise(lat, ts, ctx)
    out._value.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        out = denoise(lat, ts, ctx)
        out._value.block_until_ready()
    return round((time.perf_counter() - t0) / steps * 1000, 2)


def _resnet_bench(on_tpu: bool):
    """BASELINE config 1 (ResNet-50 ImageNet, single-device dygraph+AMP):
    images/s through a jitted train step of paddle.vision resnet50
    (reference: python/paddle/vision/models/resnet.py + the dygraph AMP
    path)."""
    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.vision.models import resnet50

    if on_tpu:
        batch, hw, steps, warmup = 64, 224, 10, 3
    else:
        batch, hw, steps, warmup = 2, 64, 8, 2
    model = resnet50(num_classes=100)
    opt = Momentum(learning_rate=0.1, momentum=0.9,
                   parameters=model.parameters())

    @jit.to_static
    def step(img, lab):
        with paddle.amp.auto_cast(level="O1"):
            loss = paddle.nn.functional.cross_entropy(model(img), lab)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.RandomState(0)
    img = paddle.to_tensor(rng.randn(batch, 3, hw, hw).astype(np.float32))
    lab = paddle.to_tensor(rng.randint(0, 100, (batch,)).astype(np.int64))
    for _ in range(warmup):
        loss = step(img, lab)
    loss._value.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(img, lab)
        loss._value.block_until_ready()
    return round(batch * steps / (time.perf_counter() - t0), 1)


def _bert_dp_bench(on_tpu: bool):
    """BASELINE config 2 (BERT-base pretraining, Fleet data-parallel):
    tokens/s through the fleet DP path — dp=2 over the host mesh when >1
    device is visible (the virtual-CPU case), single-chip otherwise
    (reference: fleet DDP over ProcessGroupNCCL; here SPMD dp sharding)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.sharding import shard_tensor
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu.optimizer import AdamW

    n_dev = len(jax.devices())
    dp = n_dev if n_dev > 1 else 1  # fleet meshes over all visible devices
    if on_tpu:
        cfg = BertConfig.base()
        batch, seq, steps, warmup = 16 * dp, 128, 10, 3
    else:
        cfg = BertConfig.tiny()
        # batch must divide over dp whatever the virtual device count is
        batch, seq, steps, warmup = dp * max(1, 8 // dp), 16, 25, 3

    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp}
    fleet.init(is_collective=True, strategy=strategy)
    try:
        model = fleet.distributed_model(BertForPretraining(cfg))
        opt = fleet.distributed_optimizer(
            AdamW(1e-4, parameters=model.parameters()))

        @jit.to_static
        def step(ids, mlm_labels, nsp):
            loss, _, _ = model(ids, masked_lm_labels=mlm_labels,
                               next_sentence_labels=nsp)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        lab = np.where(rng.rand(batch, seq) < 0.15, ids, -100).astype(
            np.int64)
        nsp = rng.randint(0, 2, (batch,)).astype(np.int64)

        def mk(a):
            t = paddle.to_tensor(a)
            return shard_tensor(t, placements=["dp"]) if dp > 1 else t

        ids_t, lab_t, nsp_t = mk(ids), mk(lab), mk(nsp)
        for _ in range(warmup):
            loss = step(ids_t, lab_t, nsp_t)
        loss._value.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids_t, lab_t, nsp_t)
            loss._value.block_until_ready()
        # per-chip so artifacts stay comparable when the visible device
        # count differs between rounds (the headline metric's convention)
        return round(batch * seq * steps
                     / (time.perf_counter() - t0) / dp, 1)
    finally:
        fleet.shutdown()


def _serving_bench(on_tpu: bool):
    """Serving throughput (paddle_tpu/serving): generated tokens/s
    through the continuous-batching engine on a staggered workload —
    requests arrive while earlier ones are mid-decode, the compiled
    paged decode step never retraces (asserted by the engine itself
    under strict_no_retrace)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import Engine, ServingConfig

    if on_tpu:
        cfg = LlamaConfig.tiny(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            dtype="bfloat16")
        scfg = ServingConfig(max_batch_size=16, block_size=32,
                             num_blocks=512)
        n_req, max_new, lens = 48, 128, (16, 48, 96, 192)
    else:
        cfg = LlamaConfig.tiny()
        scfg = ServingConfig(max_batch_size=4, block_size=8,
                             num_blocks=64)
        n_req, max_new, lens = 8, 16, (3, 8, 5, 12)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size,
                           size=(lens[i % len(lens)],)).astype(np.int32)
               for i in range(n_req)]

    # warmup: compile prefill buckets + the one decode executable
    eng = Engine(model, scfg)
    eng.generate(prompts[:len(lens)], max_new_tokens=2)

    eng = Engine(model, scfg)
    t0 = time.perf_counter()
    for p in prompts:       # staggered arrivals, decode between submits
        eng.submit(p, max_new_tokens=max_new)
        eng.step()
    eng.run_until_complete()
    dt = time.perf_counter() - t0
    tokens = eng.stats()["counters"]["tokens_generated"]
    tps = tokens / dt
    return round(tps, 1), {
        "tokens_per_sec_per_chip": round(tps / _n_chips(), 1)}


def _prefix_cache_bench(on_tpu: bool):
    """BENCH_ONLY=prefix_cache: TTFT on a shared-prefix workload
    (ISSUE 5) — N requests share a long system prompt; after the first
    request seeds the cache, every later admission reuses its prefix
    blocks and prefills only the short unique tail.  Reported value is
    the cache-off/cache-on median-TTFT ratio (> 1 means the cache wins);
    prefill compile counts and both TTFTs print to stderr.  Both modes
    run the SAME chunked prefill, so the delta is pure block reuse."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import Engine, ServingConfig

    if on_tpu:
        cfg = LlamaConfig.tiny(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            dtype="bfloat16")
        sys_len, tail_len, n_req, max_new = 1024, 64, 12, 8
        blocks, bsz, chunk = 512, 32, 256
    else:
        cfg = LlamaConfig.tiny(max_position_embeddings=512)
        sys_len, tail_len, n_req, max_new = 192, 16, 8, 4
        blocks, bsz, chunk = 128, 16, 64
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    system = rng.randint(1, cfg.vocab_size,
                         size=(sys_len,)).astype(np.int32)
    prompts = [np.concatenate([
        system,
        rng.randint(1, cfg.vocab_size, size=(tail_len,)).astype(np.int32)])
        for _ in range(n_req)]

    def run(enable):
        eng = Engine(model, ServingConfig(
            max_batch_size=4, block_size=bsz, num_blocks=blocks,
            chunk_tokens=chunk, enable_prefix_cache=enable))
        # warmup: compile both steps; with the cache on, this also
        # seeds the shared prefix (request 0's production role)
        eng.generate([prompts[0]], max_new_tokens=2)
        ttfts = []
        t0 = time.perf_counter()
        for p in prompts[1:]:   # sequential: TTFT unpolluted by batching
            req = eng.submit(p, max_new_tokens=max_new)
            eng.run_until_complete()
            ttfts.append(
                eng.metrics.requests[req.request_id].to_dict()["ttft_s"])
        dt = time.perf_counter() - t0
        eng.pool.check_leaks()  # zero leak failures is part of the bar
        tokens = eng.stats()["counters"]["tokens_generated"]
        return (float(np.median(ttfts)), eng._prefill_step.compiles,
                tokens / dt)

    off_t, off_c, _ = run(False)
    on_t, on_c, on_tps = run(True)
    ratio = off_t / on_t if on_t > 0 else float("inf")
    print(f"# prefix_cache: ttft_off={off_t * 1e3:.2f}ms "
          f"ttft_on={on_t * 1e3:.2f}ms speedup={ratio:.2f}x "
          f"prefill_compiles off={off_c} on={on_c} "
          f"(chunked: constant across all prompt lengths)",
          file=sys.stderr)
    return round(ratio, 3), {
        "tokens_per_sec_per_chip": round(on_tps / _n_chips(), 1)}


def _resilience_bench(on_tpu: bool):
    """Atomic-checkpoint roundtrip (save + verified restore) for a
    llama-sized model+optimizer state — the per-checkpoint overhead a
    ResilienceCallback adds to training.  The save path hashes and
    fsyncs every payload, so this measures the real durability cost,
    not just pickle time."""
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.resilience import ResilientCheckpointer, collect_state

    if on_tpu:
        cfg = LlamaConfig.tiny(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            dtype="bfloat16")
        rounds = 5
    else:
        cfg = LlamaConfig.tiny()
        rounds = 8
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = AdamW(1e-4, parameters=model.parameters())
    state = collect_state(model, opt)

    d = tempfile.mkdtemp(prefix="bench-resilience-")
    try:
        ck = ResilientCheckpointer(d, max_to_keep=2)
        ck.save(0, state)                      # warm page cache / dirs
        times = []
        for i in range(1, rounds + 1):
            t0 = time.perf_counter()
            ck.save(i, state)
            step, restored = ck.restore_latest()
            times.append(time.perf_counter() - t0)
            assert step == i and restored is not None
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return round(float(np.median(times)) * 1000, 2)


def _elastic_ckpt_bench(on_tpu: bool):
    """BENCH_ONLY=elastic_ckpt: sharded elastic-checkpoint roundtrip —
    a 2-process save through the owned-shard protocol (each process
    stages only its shards, the coordinator merges the per-process file
    lists and commits) followed by a verified 1-process restore that
    reassembles the global arrays (restore-with-reshard).  The
    single-file atomic roundtrip of the SAME state rides along so the
    artifact shows the protocol's overhead vs the legacy format."""
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.distributed import bootstrap
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.resilience import ResilientCheckpointer, collect_state

    if on_tpu:
        cfg = LlamaConfig.tiny(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            dtype="bfloat16")
        rounds = 5
    else:
        cfg = LlamaConfig.tiny()
        rounds = 8
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = AdamW(1e-4, parameters=model.parameters())
    state = collect_state(model, opt)

    def sharded_roundtrip(d, step):
        # per-process saves, coordinator LAST (it merges + commits);
        # under emulation the protocol runs sequentially in-process,
        # so the measured cost is the full fleet's I/O, not one host's
        for idx in (1, 0):
            with bootstrap.emulated_process_context(idx, 2):
                ResilientCheckpointer(d, max_to_keep=2).save(step, state)
        ck = ResilientCheckpointer(d, max_to_keep=2)
        got, restored = ck.restore_latest()
        assert got == step and restored is not None
        assert ck.reshard_restores == 1   # 2-process ckpt, 1-process read

    d_shard = tempfile.mkdtemp(prefix="bench-elastic-")
    d_single = tempfile.mkdtemp(prefix="bench-elastic-single-")
    try:
        sharded_roundtrip(d_shard, 0)          # warm page cache / dirs
        times = []
        for i in range(1, rounds + 1):
            t0 = time.perf_counter()
            sharded_roundtrip(d_shard, i)
            times.append(time.perf_counter() - t0)
        ck = ResilientCheckpointer(d_single, max_to_keep=2, sharded=False)
        ck.save(0, state)
        single = []
        for i in range(1, rounds + 1):
            t0 = time.perf_counter()
            ck.save(i, state)
            got, restored = ck.restore_latest()
            assert got == i and restored is not None
            single.append(time.perf_counter() - t0)
    finally:
        shutil.rmtree(d_shard, ignore_errors=True)
        shutil.rmtree(d_single, ignore_errors=True)
    return (round(float(np.median(times)) * 1000, 2),
            {"single_file_roundtrip_ms":
             round(float(np.median(single)) * 1000, 2)})


def _observe_overhead_bench(on_tpu: bool):
    """Per-step cost of the observability registry: the same compiled
    training loop timed with telemetry OFF (the no-op fast path every
    untelemetered run takes) and ON (StepTimer + compile tracking +
    registry mirrors), alternating passes for noise robustness.  Returns
    the on-vs-off overhead in percent — the ISSUE 4 acceptance gate is
    < 2%."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, observability
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    steps, batch, seq = (30, 4, 64) if on_tpu else (30, 2, 16)
    paddle.seed(0)
    net = LlamaForCausalLM(LlamaConfig.tiny(max_position_embeddings=seq))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.AdamW(1e-3,
                                         parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    rng = np.random.RandomState(1)
    ids = rng.randint(1, 256, size=(steps, batch, seq + 1)).astype(np.int64)
    batches = [(a[:, :-1], a[:, 1:]) for a in ids]

    def one_pass():
        t0 = time.perf_counter()
        model.fit(train_data=batches, epochs=1, verbose=0)
        return (time.perf_counter() - t0) / steps

    one_pass()                                   # compile + warm caches
    prev = observability.enable(False)
    ratios = []
    try:
        # paired passes, alternating which side runs first each round:
        # adjacent runs see the same machine state, so clock drift and
        # cache effects cancel inside each per-round ratio, and the
        # median of ratios shrugs off outlier rounds entirely
        for i in range(9):
            on_first = bool(i % 2)
            observability.enable(on_first)
            first = one_pass()
            observability.enable(not on_first)
            second = one_pass()
            on_t, off_t = (first, second) if on_first else (second, first)
            ratios.append((on_t - off_t) / off_t * 100)
    finally:
        observability.enable(prev)
    return round(float(np.median(ratios)), 2)


def _mesh_train_bench(on_tpu: bool):
    """BENCH_ONLY=mesh_train: per-chip training throughput under the
    runtime MeshExecutor — the same tiny-llama hapi loop on a (1,1,1)
    mesh and on (data=2,fsdp=2,tp=2).  Returns tokens/sec/chip for the
    sharded run (the number that should hold as the mesh grows); the
    single-chip figure and the achieved scaling ratio go to stderr.
    On hosts with fewer than 8 devices the executor degrades to the
    devices it has (CPU runs want XLA_FLAGS=
    --xla_force_host_platform_device_count=8, as tools/ci.sh sets)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    steps, batch, seq = (30, 8, 128) if on_tpu else (20, 4, 16)
    rng = np.random.RandomState(1)
    ids = rng.randint(1, 256, size=(batch, seq + 1)).astype(np.int64)
    x, y = ids[:, :-1], ids[:, 1:]

    def run(axes):
        paddle.seed(0)
        net = LlamaForCausalLM(
            LlamaConfig.tiny(max_position_embeddings=seq))
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.AdamW(1e-3, parameters=net.parameters()),
            nn.CrossEntropyLoss(), mesh=axes)
        ex = model._mesh_executor
        for _ in range(3):                     # compile both entries
            model.train_batch([x], [y])
        t0 = time.perf_counter()
        for _ in range(steps):
            model.train_batch([x], [y])        # loss .numpy() syncs
        dt = time.perf_counter() - t0
        chips = max(1, ex.mesh.size)
        tps_chip = steps * batch * seq / dt / chips
        ex.close()
        return tps_chip, chips

    single_tps, _ = run({"data": 1, "fsdp": 1, "tp": 1})
    mesh_tps, chips = run({"data": 2, "fsdp": 2, "tp": 2})
    print(f"mesh_train: single-chip {single_tps:.1f} tok/s, "
          f"{chips}-chip mesh {mesh_tps:.1f} tok/s/chip "
          f"(scaling {mesh_tps / single_tps:.2f}x per chip)",
          file=sys.stderr)
    return round(float(mesh_tps), 2)


def _overload_bench(on_tpu: bool):
    """BENCH_ONLY=overload: goodput under a seeded overload burst with
    load shedding on vs off (README: Overload control).  The same burst
    runs twice under an injected per-step slowdown: four 96-token
    requests whose deadline the slowdown makes hopeless (the injected
    sleeps alone exceed it, so the outcome is machine-independent),
    two short feasible requests with the same deadline, and two
    deadline-free requests whose TTFT measures queueing delay.  With
    shedding OFF the hopeless work occupies every decode slot until it
    times out, so the feasible requests bust their own deadline waiting;
    with shedding ON it is rejected at admission and they complete.
    Reported value is the on/off goodput ratio (> 1 means shedding
    converts wasted work into met deadlines); shed rate and p99 TTFT
    for both modes print to stderr."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.resilience.chaos import FaultPlan, burst_prompts
    from paddle_tpu.serving import Engine, ServingConfig

    delay_s, deadline_s = 0.03, 0.7
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()

    def run(shed_on):
        eng = Engine(model, ServingConfig(
            max_batch_size=4, block_size=4, num_blocks=64,
            chunk_tokens=4, max_queue_len=32,
            enable_load_shedding=shed_on))
        with FaultPlan(seed=11, step_delay_s=delay_s):
            # warm under the slowdown so the latency EWMAs (and thus
            # the shed estimate) reflect the conditions of the burst
            eng.submit(burst_prompts(seed=1, n=1, min_len=8,
                                     max_len=8)[0], max_new_tokens=4)
            eng.run_until_complete()
            reqs = []
            for p in burst_prompts(seed=11, n=4, min_len=96,
                                   max_len=96):    # hopeless vs deadline
                reqs.append(eng.submit(p, max_new_tokens=4,
                                       deadline_s=deadline_s))
            for p in burst_prompts(seed=2, n=2, min_len=8, max_len=8):
                reqs.append(eng.submit(p, max_new_tokens=4,
                                       deadline_s=deadline_s))
            for p in burst_prompts(seed=3, n=2, min_len=8, max_len=8):
                reqs.append(eng.submit(p, max_new_tokens=4))
            eng.run_until_complete()
        eng.pool.check_leaks()
        c = eng.stats()["counters"]
        ttfts = [m.to_dict()["ttft_s"]
                 for m in eng.metrics.requests.values()
                 if m.to_dict()["ttft_s"] is not None]
        p99 = float(np.percentile(ttfts, 99)) if ttfts else float("nan")
        return (c["goodput_tokens"], c["requests_shed"],
                c["requests_shed"] / len(reqs), p99,
                c["tokens_generated"])

    g_off, shed_off, rate_off, p99_off, _ = run(False)
    t_mid = time.perf_counter()
    g_on, shed_on, rate_on, p99_on, tok_on = run(True)
    dt_on = time.perf_counter() - t_mid
    assert shed_off == 0                 # nothing sheds with it off
    ratio = g_on / g_off if g_off > 0 else float("inf")
    print(f"# overload: goodput off={g_off} on={g_on} tokens "
          f"(ratio {ratio:.2f}x), shed rate off={rate_off:.2f} "
          f"on={rate_on:.2f}, p99 ttft off={p99_off * 1e3:.1f}ms "
          f"on={p99_on * 1e3:.1f}ms", file=sys.stderr)

    # --- fixed-HBM int8-vs-fp32: same kv_pool_bytes budget, same
    # KV-limited burst.  The quantized pool fits ~3.5x the blocks, so
    # more requests decode CONCURRENTLY: occupancy = generated tokens
    # per decode iteration, goodput = tokens per second under an
    # injected per-step delay that dominates wall-clock (so the ratio
    # tracks the iteration count, not host speed).  ISSUE 20's
    # headline: both strictly higher at int8, occupancy >= 1.5x.
    from paddle_tpu.serving.cache import BlockKVPool

    hbm = 12 * BlockKVPool.block_bytes_for(
        model.config.num_hidden_layers, 4,
        model.config.num_key_value_heads,
        model.config.hidden_size // model.config.num_attention_heads,
        model.config.dtype, None)
    quant = {}
    for kv_dtype in (None, "int8"):
        eng = Engine(model, ServingConfig(
            max_batch_size=8, block_size=4, num_blocks=None,
            kv_pool_bytes=hbm, kv_cache_dtype=kv_dtype,
            chunk_tokens=16, max_queue_len=64))
        burst = burst_prompts(seed=7, n=12, min_len=10, max_len=14)
        # warm OUTSIDE the timed region: the int8 step kinds compile
        # fresh here while the fp32 kinds were compiled by the shed
        # phase above — timing compiles would swamp the serve loop
        eng.submit(burst_prompts(seed=1, n=1, min_len=8, max_len=8)[0],
                   max_new_tokens=2)
        eng.run_until_complete()
        warm = eng.stats()["counters"]
        base = (warm["tokens_generated"], warm["decode_iterations"])
        t0 = time.perf_counter()
        # delay large enough to dominate the host-side step cost, so
        # the goodput ratio tracks iteration count (machine-independent)
        with FaultPlan(seed=7, step_delay_s=0.01):
            for p in burst:
                eng.submit(p, max_new_tokens=8)
            eng.run_until_complete()
        dt = time.perf_counter() - t0
        eng.pool.check_leaks()
        c = eng.stats()["counters"]
        toks = c["tokens_generated"] - base[0]
        iters = c["decode_iterations"] - base[1]
        quant[kv_dtype] = {
            "blocks": eng.num_blocks,
            "occupancy": toks / iters,
            "goodput_tps": toks / dt,
        }
    occ_ratio = quant["int8"]["occupancy"] / quant[None]["occupancy"]
    gp_ratio = quant["int8"]["goodput_tps"] / quant[None]["goodput_tps"]
    print(f"# overload fixed-HBM ({hbm} B): fp32 "
          f"{quant[None]['blocks']} blocks occ="
          f"{quant[None]['occupancy']:.2f} vs int8 "
          f"{quant['int8']['blocks']} blocks occ="
          f"{quant['int8']['occupancy']:.2f} "
          f"(occupancy {occ_ratio:.2f}x, goodput {gp_ratio:.2f}x)",
          file=sys.stderr)
    return round(float(ratio), 3), {
        "tokens_per_sec_per_chip": round(
            tok_on / dt_on / _n_chips(), 1),
        "int8_occupancy_ratio_fixed_hbm": round(occ_ratio, 3),
        "int8_goodput_ratio_fixed_hbm": round(gp_ratio, 3),
        "fixed_hbm_blocks_fp32": quant[None]["blocks"],
        "fixed_hbm_blocks_int8": quant["int8"]["blocks"]}


def _spec_decode_bench(on_tpu: bool):
    """BENCH_ONLY=spec_decode: goodput under deadline pressure with
    speculative decoding on vs off (README: Sampling, speculative
    decoding & streaming).  The same requests run twice under an
    injected per-step slowdown (FaultPlan step_delay_s, so the outcome
    is machine-independent): the plain engine pays one delayed decode
    step per token, while the speculative engine pays two delayed steps
    (draft scan + verify) per K+1 committed tokens — with K=3 and a
    weight-identical draft (accept rate 1.0, the CEILING a real distilled
    draft approaches; reported as such) that is 2 steps per 4 tokens,
    a 2x wall-clock win the deadline is tuned to detect.  Deadline-bound
    requests finish inside their SLO only with speculation on, so PR
    10's goodput counter moves; a deadline-free request keeps the OFF
    goodput nonzero so the ratio stays finite.  Reported value is the
    on/off goodput ratio (> 1 means speculation converts busted
    deadlines into met ones); accept rate, TPOT speedup and
    tokens/sec/chip ride in the JSON line."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.resilience.chaos import FaultPlan, burst_prompts
    from paddle_tpu.serving import (Engine, ServingConfig,
                                    SpeculativeConfig)

    k_draft, delay_s, deadline_s = 4, 0.03, 0.9
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()

    def run(spec_on):
        eng = Engine(model, ServingConfig(
            max_batch_size=4, block_size=4, num_blocks=96,
            chunk_tokens=16, max_queue_len=32,
            speculative=(SpeculativeConfig(draft_model=model,
                                           num_draft_tokens=k_draft)
                         if spec_on else None)))
        # warm OUTSIDE the fault plan: compile time must not eat into
        # the deadline comparison
        eng.generate(burst_prompts(seed=1, n=1, min_len=8, max_len=8),
                     max_new_tokens=k_draft + 2)
        reqs = []
        with FaultPlan(seed=11, step_delay_s=delay_s):
            t0 = time.perf_counter()
            # 41 tokens of injected sleep: ~42 delayed steps (1.26s)
            # off; on, ~ceil(40/5)=8 verify iterations at TWO delayed
            # steps each (draft scan + verify) plus two delayed prefill
            # pairs — ~0.6s, comfortably inside the 0.9s deadline
            reqs.append(eng.submit(
                burst_prompts(seed=5, n=1, min_len=8, max_len=8)[0],
                max_new_tokens=41, deadline_s=deadline_s))
            reqs.append(eng.submit(
                burst_prompts(seed=6, n=1, min_len=8, max_len=8)[0],
                max_new_tokens=5))
            eng.run_until_complete()
            dt = time.perf_counter() - t0
        eng.pool.check_leaks()
        c = eng.stats()["counters"]
        tok = sum(len(r.generated) for r in reqs)
        return (c["goodput_tokens"], tok, dt,
                eng.metrics.spec_accept_rate())

    g_off, tok_off, dt_off, _ = run(False)
    g_on, tok_on, dt_on, accept = run(True)
    ratio = g_on / g_off if g_off > 0 else float("inf")
    tpot_speedup = (tok_on / dt_on) / (tok_off / dt_off)
    print(f"# spec_decode: goodput off={g_off} on={g_on} tokens "
          f"(ratio {ratio:.2f}x), accept_rate={accept:.3f} "
          f"(weight-identical draft ceiling), K={k_draft}, "
          f"tpot speedup {tpot_speedup:.2f}x", file=sys.stderr)
    return round(float(ratio), 3), {
        "spec_accept_rate": round(float(accept), 4),
        "spec_tpot_speedup": round(float(tpot_speedup), 3),
        "tokens_per_sec_per_chip": round(
            tok_on / dt_on / _n_chips(), 1)}


def _router_replay_bench(on_tpu: bool):
    """BENCH_ONLY=router_replay: the serving fleet router on a seeded
    multi-tenant trace (serving/replay.py), prefix-affinity placement
    vs round-robin on IDENTICAL fleets and the IDENTICAL trace (README:
    Serving fleet & router).  The trace mixes a chatty tenant sharing a
    long system prompt, a long-prompt tenant, and a burst tenant.
    Reported value is the affinity fleet's realized cached-token ratio
    (prompt tokens served from replica prefix caches); the round-robin
    ratio, both p99 TTFTs, and per-tenant goodput ride in the JSON line
    and print to stderr.  Affinity must beat round-robin on the ratio —
    round-robin scatters a tenant's requests across replicas, so each
    replica re-prefills the shared prefix — and not lose on p99 TTFT."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (Engine, Router, ServingConfig,
                                    Tenant, build_trace, replay_trace)

    if on_tpu:
        cfg = LlamaConfig.tiny(max_position_embeddings=1024)
        tenants = [
            Tenant("chat", kind="chat", requests=16,
                   shared_prefix_tokens=192, tail_tokens=(8, 32),
                   max_new_tokens=8),
            Tenant("long", kind="long", requests=6,
                   shared_prefix_tokens=32, tail_tokens=(128, 256),
                   max_new_tokens=6),
            Tenant("burst", kind="burst", requests=12,
                   shared_prefix_tokens=64, tail_tokens=(4, 16),
                   max_new_tokens=4),
        ]
        blocks, bsz, chunk, horizon = 256, 16, 64, 24
    else:
        cfg = LlamaConfig.tiny()
        # shared prefixes dominate each prompt, so consolidation (one
        # prefix copy fleet-wide) vs duplication (one per replica) is
        # the measured difference, well clear of timing noise
        tenants = [
            Tenant("chat", kind="chat", requests=12,
                   shared_prefix_tokens=96, tail_tokens=(4, 12),
                   max_new_tokens=6),
            Tenant("long", kind="long", requests=4,
                   shared_prefix_tokens=16, tail_tokens=(48, 80),
                   max_new_tokens=4),
            Tenant("burst", kind="burst", requests=10,
                   shared_prefix_tokens=48, tail_tokens=(2, 8),
                   max_new_tokens=4),
        ]
        blocks, bsz, chunk, horizon = 128, 4, 32, 16
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()

    def fleet(policy):
        def rcfg(name):
            return ServingConfig(
                name=name, max_batch_size=4, block_size=bsz,
                num_blocks=blocks, chunk_tokens=chunk, max_queue_len=48)

        # weight high enough that transient queue imbalance never
        # unsticks a tenant from its prefix replica mid-trace
        return Router([Engine(model, rcfg(f"{policy[:2]}-0")),
                       Engine(model, rcfg(f"{policy[:2]}-1"))],
                      policy=policy, seed=0, affinity_weight=8.0)

    # warm ONCE: the compiled steps cache on the MODEL keyed by the
    # weights fingerprint, so every replica below reuses them and the
    # replayed TTFTs are compile-free
    warm = Engine(model, ServingConfig(max_batch_size=4, block_size=bsz,
                                       num_blocks=blocks,
                                       chunk_tokens=chunk))
    warm.generate([np.arange(1, chunk + 2, dtype=np.int32)],
                  max_new_tokens=2)

    trace = build_trace(tenants, seed=7, horizon=horizon,
                        vocab=cfg.vocab_size)
    # placement is deterministic per policy (identical logs every
    # repeat) but the fleet p99 TTFT is a max over ~a dozen wall-clock
    # samples — replay each fleet three times on FRESH replicas and
    # take the median p99 so scheduler jitter can't flip the headline
    # comparison either way
    reps = {"affinity": [], "round_robin": []}
    t0 = dt = None
    for _ in range(3):
        for policy in reps:
            if policy == "affinity":
                t0 = time.perf_counter()
            reps[policy].append(replay_trace(fleet(policy), trace))
            if policy == "affinity" and dt is None:
                dt = time.perf_counter() - t0
    aff, rr = reps["affinity"][0], reps["round_robin"][0]

    def med(runs, key):
        vals = sorted(r["fleet"][key] or 0 for r in runs)
        return vals[len(vals) // 2]

    # the ratio is NEARLY deterministic (cold placements are; once the
    # EWMAs warm a rare load spill can move one request), so the median
    # smooths both headline numbers the same way
    a_ratio = med(reps["affinity"], "cached_token_ratio")
    r_ratio = med(reps["round_robin"], "cached_token_ratio")
    a_p99 = med(reps["affinity"], "p99_ttft_s")
    r_p99 = med(reps["round_robin"], "p99_ttft_s")
    goodput = sum(t["goodput_tokens"] for t in aff["tenants"].values())
    assert a_ratio >= r_ratio, (a_ratio, r_ratio)
    per_tenant = " ".join(
        f"{name}:{t['goodput_tokens']}tok/p99="
        f"{(t['p99_ttft_s'] or 0) * 1e3:.1f}ms"
        for name, t in aff["tenants"].items())
    print(f"# router_replay: cached_ratio affinity={a_ratio:.3f} "
          f"round_robin={r_ratio:.3f}, p99 ttft affinity="
          f"{(a_p99 or 0) * 1e3:.1f}ms round_robin="
          f"{(r_p99 or 0) * 1e3:.1f}ms, placements="
          f"{aff['fleet']['placements']}, {per_tenant}",
          file=sys.stderr)
    return round(float(a_ratio), 4), {
        "round_robin_cached_token_ratio": round(float(r_ratio), 4),
        "affinity_p99_ttft_ms": a_p99 and round(a_p99 * 1e3, 2),
        "round_robin_p99_ttft_ms": r_p99 and round(r_p99 * 1e3, 2),
        "goodput_tokens": goodput,
        "tokens_per_sec_per_chip": round(goodput / dt / _n_chips(), 1)}


def _paged_attn_bench(on_tpu: bool):
    """BENCH_ONLY=paged_attn: fused vs scatter/gather paged-attention
    decode (kernels/paged_attention).  Times the COMPILED paged decode
    step — the whole serving TPOT unit — with the fused kernel pinned
    on vs off, on identical shapes and pool state: same model, same
    block tables, same mid-stream frontiers.  Reported value is the
    fused decode step time (TPOT) in ms; the unfused time and the
    speedup ride in the JSON line and print to stderr."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import make_paged_decode_step

    if on_tpu:
        cfg = LlamaConfig.tiny(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            dtype="bfloat16")
        B, bs, nbs, steps, warmup = 16, 32, 64, 50, 8
    else:
        cfg = LlamaConfig.tiny()
        B, bs, nbs, steps, warmup = 4, 8, 8, 10, 2
    nb = 1 + B * nbs        # block 0 reserved as the garbage block
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    kvh = cfg.num_key_value_heads
    hd = cfg.hidden_size // cfg.num_attention_heads
    dt_kv = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    pools = [(jnp.zeros((nb, bs, kvh, hd), dt_kv),
              jnp.zeros((nb, bs, kvh, hd), dt_kv))
             for _ in range(cfg.num_hidden_layers)]
    bt = jnp.asarray(1 + np.arange(B * nbs).reshape(B, nbs), jnp.int32)
    # mid-stream frontiers at 3/4 of max context: the gather/split-K
    # sweep has real work, matching steady-state decode
    ctx = (bs * nbs * 3) // 4
    lengths = jnp.asarray(np.full(B, ctx), jnp.int32)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(1, cfg.vocab_size, (B, 1)), jnp.int32)

    def time_step(step, p=pools):
        jax.block_until_ready(step(tok, p, bt, lengths)[0])  # compile
        for _ in range(warmup):
            jax.block_until_ready(step(tok, p, bt, lengths)[0])
        t0 = time.perf_counter()
        for _ in range(steps):
            jax.block_until_ready(step(tok, p, bt, lengths)[0])
        return (time.perf_counter() - t0) / steps

    t_unfused = time_step(make_paged_decode_step(model, fused=False))
    t_fused = time_step(make_paged_decode_step(model, fused=True))
    # quantized TPOT: the same step over an int8 pool (codes + per-row
    # scale sidecars) — the DMA-boundary dequant path, 4x fewer KV
    # bytes per decode step than fp32 (2x vs bf16)
    pools_q = [(jnp.zeros((nb, bs, kvh, hd), jnp.int8),
                jnp.zeros((nb, bs, kvh, hd), jnp.int8),
                jnp.ones((nb, bs), jnp.float32),
                jnp.ones((nb, bs), jnp.float32))
               for _ in range(cfg.num_hidden_layers)]
    t_int8 = time_step(make_paged_decode_step(model, fused=True,
                                              kv_cache_dtype="int8"),
                       p=pools_q)
    speedup = t_unfused / t_fused if t_fused > 0 else float("inf")
    q_speedup = t_fused / t_int8 if t_int8 > 0 else float("inf")
    print(f"# paged_attn: decode step unfused={t_unfused * 1e3:.3f}ms "
          f"fused={t_fused * 1e3:.3f}ms speedup={speedup:.2f}x "
          f"int8={t_int8 * 1e3:.3f}ms ({q_speedup:.2f}x vs fused) "
          f"(B={B}, ctx={ctx}, block_size={bs})", file=sys.stderr)
    return round(t_fused * 1e3, 3), {
        "unfused_tpot_ms": round(t_unfused * 1e3, 3),
        "fused_vs_unfused_speedup": round(speedup, 3),
        "int8_kv_tpot_ms": round(t_int8 * 1e3, 3),
        "int8_vs_fused_speedup": round(q_speedup, 3),
        "tokens_per_sec_per_chip": round(B / t_fused / _n_chips(), 1)}


def _fusion_miner_bench(on_tpu: bool):
    """BENCH_ONLY=fusion_miner: predicted-vs-measured HBM-byte savings
    of the mined chunked-prefill fusion — a standing test of the
    miner's cost model.  Predicted = the fusion miner's bytes-saved for
    the above-threshold candidates on the UNFUSED prefill trace;
    measured = the xray-priced byte delta between the unfused and fused
    prefill programs (fused traced under force_pallas_interpret so the
    pallas kernels price through kernels/costs).  The ratio must stay
    within 2x in either direction, or the byte model has drifted from
    what fusing actually buys.  Wall-clock of the compiled fused vs
    unfused prefill step rides along in the JSON line."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.analysis import fusionminer, xray
    from paddle_tpu.kernels.fusion import force_pallas_interpret
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import make_chunked_prefill_step

    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    net = LlamaForCausalLM(cfg)
    net.eval()
    _, prefill_args = xray._serving_abstract_args(
        net, batch=4, num_blocks=32, block_size=8, max_blocks_per_seq=8,
        chunk_tokens=32)

    rep = fusionminer.mine(
        make_chunked_prefill_step(net, fused=False), prefill_args,
        name="serving::prefill_step", chip="v5e",
        threshold_bytes=fusionminer.DEFAULT_THRESHOLD_BYTES)
    predicted = sum(c.bytes_saved for c in rep.above_threshold())
    unfused_rep = xray.analyze(
        make_chunked_prefill_step(net, fused=False), prefill_args,
        name="u", chip="v5e")
    with force_pallas_interpret():
        fused_rep = xray.analyze(
            make_chunked_prefill_step(net, fused=True), prefill_args,
            name="f", chip="v5e")
    measured = unfused_rep.bytes - fused_rep.bytes
    ratio = predicted / measured if measured else float("inf")
    assert 0.5 <= ratio <= 2.0, (
        f"miner predicted {predicted:.0f}B but fusing removed "
        f"{measured:.0f}B of priced traffic (ratio {ratio:.2f})")

    # compiled-step wall clock, fused vs unfused, same shapes/state
    B, bs, nbs, C = 1, 8, 8, 32
    nb = 1 + B * nbs
    kvh = cfg.num_key_value_heads
    hd = cfg.hidden_size // cfg.num_attention_heads
    steps, warmup = (50, 8) if on_tpu else (10, 2)
    pools = [(jnp.zeros((nb, bs, kvh, hd), jnp.float32),
              jnp.zeros((nb, bs, kvh, hd), jnp.float32))
             for _ in range(cfg.num_hidden_layers)]
    bt = jnp.asarray(1 + np.arange(B * nbs).reshape(B, nbs), jnp.int32)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(1, cfg.vocab_size, (B, C)), jnp.int32)
    start = jnp.zeros((B,), jnp.int32)
    last = jnp.asarray(C - 1, jnp.int32)

    def time_step(step):
        jax.block_until_ready(step(ids, pools, bt, start, last)[0])
        for _ in range(warmup):
            jax.block_until_ready(step(ids, pools, bt, start, last)[0])
        t0 = time.perf_counter()
        for _ in range(steps):
            jax.block_until_ready(step(ids, pools, bt, start, last)[0])
        return (time.perf_counter() - t0) / steps

    t_unfused = time_step(make_chunked_prefill_step(net, fused=False))
    t_fused = time_step(make_chunked_prefill_step(net, fused=True))
    speedup = t_unfused / t_fused if t_fused > 0 else float("inf")
    print(f"# fusion_miner: predicted={predicted / 1024.0:.1f}KiB "
          f"measured={measured / 1024.0:.1f}KiB ratio={ratio:.2f} "
          f"(top: {rep.candidates[0].code} rank 1), prefill chunk "
          f"unfused={t_unfused * 1e3:.3f}ms fused={t_fused * 1e3:.3f}ms "
          f"speedup={speedup:.2f}x", file=sys.stderr)
    return round(float(ratio), 3), {
        "predicted_kib": round(predicted / 1024.0, 1),
        "measured_kib": round(measured / 1024.0, 1),
        "unfused_prefill_ms": round(t_unfused * 1e3, 3),
        "fused_prefill_ms": round(t_fused * 1e3, 3),
        "fused_vs_unfused_speedup": round(speedup, 3)}


def _moe_plan_bench(on_tpu):
    """BENCH_ONLY=moe_plan: static shard-plan metrics for the MoE block
    on the canonical expert mesh — no devices touched, the number is the
    analyzer's wire-byte estimate, so a routing/propagation regression
    (a2a pair stops firing, an unplanned gather appears) moves the
    artifact even on CPU-only rounds."""
    del on_tpu  # the plan is abstract: same answer on every backend
    from paddle_tpu.analysis.shardplan import audit_shardplan

    (rep,) = audit_shardplan(steps=("moe",))
    unplanned = sum(1 for c in rep.collectives if not c.planned)
    a2a = sum(1 for c in rep.collectives if c.kind == "all_to_all")
    by_dtype = {k: int(v) for k, v in
                sorted(rep.per_chip_peak_hbm_by_dtype.items())}
    print(f"# moe_plan: comm={int(rep.comm_bytes)}B on wire, "
          f"{len(rep.collectives)} collectives ({a2a} all_to_all, "
          f"{unplanned} unplanned), per-chip peak HBM "
          f"{rep.per_chip_peak_hbm_bytes}B by dtype {by_dtype}, "
          f"{len(rep.errors())} error(s)", file=sys.stderr)
    assert unplanned == 0 and not rep.errors()
    return round(rep.comm_bytes / 1024.0, 3)


def _dcn_plan_bench(on_tpu):
    """BENCH_ONLY=dcn_plan: multi-host shard-plan metrics — the five
    registered steps priced on an emulated 2-host x (2,2) topology.  No
    devices touched; the number is the analyzer's DCN wire-byte
    estimate, so a decomposition regression (a host-crossing collective
    stops splitting into ICI + DCN phases, an axis silently lands on
    the wrong link level) moves the artifact even on CPU-only rounds."""
    del on_tpu  # the plan is abstract: same answer on every backend
    from paddle_tpu.analysis.shardplan import (Topology, audit_shardplan,
                                               recommend_layouts)

    topo = Topology(hosts=2, chips_per_host=(2, 2))
    reports = audit_shardplan(topology=topo)
    unplanned = sum(1 for r in reports for c in r.collectives
                    if not c.planned)
    n_err = sum(len(r.errors()) for r in reports)
    ici = sum(r.ici_comm_bytes for r in reports)
    dcn = sum(r.dcn_comm_bytes for r in reports)
    host_hbm = max(r.per_host_peak_hbm_bytes for r in reports)
    train = next(r for r in reports if "train" in r.name)
    top = recommend_layouts(train)[0]
    print(f"# dcn_plan: {len(reports)} step(s) on 2 host(s) x (2,2), "
          f"wire ICI={ici / 1024.0:.1f}KiB DCN={dcn / 1024.0:.1f}KiB, "
          f"per-host peak HBM {host_hbm}B, {unplanned} unplanned, "
          f"{n_err} error(s), train top layout: {top.describe()}",
          file=sys.stderr)
    assert unplanned == 0 and n_err == 0
    return round(dcn / 1024.0, 3)


def _run_single(which: str, on_tpu: bool):
    """BENCH_ONLY=<name>: run ONE secondary workload as its own artifact
    (VERDICT r4 weak #2 — 'extras timed out' zeroed resnet/bert/unet for
    four rounds; individually they get their own process + time budget)."""
    fns = {"moe": _moe_bench, "unet": _unet_bench, "resnet": _resnet_bench,
           "bert": _bert_dp_bench, "serve_llama": _serving_bench,
           "prefix_cache": _prefix_cache_bench,
           "resilient_train": _resilience_bench,
           "elastic_ckpt": _elastic_ckpt_bench,
           "observe_overhead": _observe_overhead_bench,
           "mesh_train": _mesh_train_bench,
           "overload": _overload_bench,
           "spec_decode": _spec_decode_bench,
           "router_replay": _router_replay_bench,
           "moe_plan": _moe_plan_bench,
           "dcn_plan": _dcn_plan_bench,
           "paged_attn": _paged_attn_bench,
           "fusion_miner": _fusion_miner_bench}
    metric, unit = _ONLY_METRICS[which]
    value = fns[which](on_tpu)
    extras = {}
    if isinstance(value, tuple):
        value, extras = value
    out = {"metric": metric, "value": value, "unit": unit,
           "vs_baseline": None}
    out.update(extras)   # serving headlines: tokens_per_sec_per_chip &c.
    _emit(out)


def run_bench():
    import os

    devices, backend = _init_backend()
    on_tpu = backend == "tpu"
    device_kind = devices[0].device_kind if devices else "unknown"

    which = os.environ.get("BENCH_ONLY", "")
    if which:
        _run_single(which, on_tpu)
        return

    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW

    bench_config = os.environ.get("BENCH_CONFIG", "")
    if on_tpu and bench_config == "llama1b_s4096":
        # North-star-shaped memory proof (VERDICT r5 item 3): ~1.10B-param
        # Llama (TinyLlama-1.1B plan: h2048/i5632/22L/32h/4kv) at s4096,
        # bf16, per-layer remat + donated train state + chunked fused
        # lm-head loss.  Validates the remat/donation/HBM story the 8B
        # extrapolation rests on, on one 16 GB v5e.
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=22, num_attention_heads=32,
            num_key_value_heads=4, max_position_embeddings=4096,
            dtype="bfloat16", recompute=True)
        batch, seq, steps, warmup = 4, 4096, 10, 3
        batch = int(os.environ.get("BENCH_BATCH", batch))
    elif on_tpu:
        # 603M-param Llama (hidden 2048 → 128-lane-aligned matmuls that
        # saturate the MXU).  Fits one v5e chip with the chunked fused
        # lm-head loss; measured MFU ~0.47 vs 0.22 for the old h1024 config.
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=10, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=2048,
            dtype="bfloat16")
        batch, seq, steps, warmup = 8, 2048, 20, 5
        # experiment knob (tools/run_tpu_experiments.sh): batch override
        batch = int(os.environ.get("BENCH_BATCH", batch))
    else:  # smoke path for CPU dev runs
        cfg = LlamaConfig.tiny()
        if bench_config == "llama1b_s4096":
            cfg.recompute = True  # exercise the remat path on CPU too
        batch, seq, steps, warmup = 2, 64, 5, 2
    cfg.fused_lm_loss = True  # opt-in: bench never consumes the logits

    rng = np.random.RandomState(0)
    initial_batch = batch
    while True:
        # model/opt/jit rebuilt per attempt: an execution-time OOM fires
        # AFTER the params were donated to the failed executable
        # (jit donates argnum 0), so retrying with the old state would
        # die on deleted buffers instead of succeeding at half batch
        model = LlamaForCausalLM(cfg)
        opt = AdamW(1e-4, parameters=model.parameters())

        @jit.to_static
        def train_step(tokens):
            loss, _ = model(tokens, labels=tokens)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        tokens = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
        try:
            for _ in range(warmup):
                loss = train_step(tokens)
            np.asarray(loss.numpy())  # hard sync
            break
        except Exception as e:  # noqa: BLE001
            # adaptive batch: an OOM config must cost throughput, not the
            # artifact (the tunnel-up window is the scarce resource)
            if on_tpu and batch > 1 and "RESOURCE_EXHAUSTED" in str(e):
                print(f"# OOM at batch={batch}; retrying with "
                      f"batch={batch // 2}", file=sys.stderr)
                batch //= 2
                continue
            raise

    # tail sync (standard XLA benching: dispatch all steps, block once) —
    # each step's loss depends on the previous step's donated state, so
    # the final block covers the whole chain; per-step sync pays a full
    # tunnel RTT per step on remote backends and understates chip perf.
    # A second timed pass with per-step sync runs later UNDER THE
    # WATCHDOG (a tunnel death mid-pass must not forfeit this number)
    # and is reported as an extra for cross-round comparability with the
    # per-step-sync 20260731T0316Z artifact.
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = train_step(tokens)
    loss._value.block_until_ready()
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt

    # params (embedding counted once) for 6N flops/token + attention term
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = (6.0 * n_params
                       + 12.0 * cfg.num_hidden_layers * cfg.hidden_size * seq)
    achieved_flops = tokens_per_sec * flops_per_token
    peak = _peak_flops(device_kind) if on_tpu else None
    mfu = achieved_flops / peak if peak else None
    if on_tpu and peak is None:
        print(f"# unknown TPU device_kind={device_kind!r}; "
              "cannot compute MFU", file=sys.stderr)

    # secondary workloads (VERDICT r2 #7/#8): never let them sink the
    # headline number — errors land in stderr, fields stay null.  A HANG
    # (tunnel dying mid-extra: block_until_ready never returns) would
    # forfeit the measured headline too, so a watchdog thread emits the
    # headline-only JSON line and exits the process if the extras phase
    # overruns its budget (jax device waits release the GIL, so the timer
    # fires even while the main thread is stuck in a C++ wait).
    import threading

    headline = {
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 4) if mfu is not None else None,
    }
    skip_extras = os.environ.get("BENCH_EXTRAS", "1") == "0"
    # record the ACTUAL run shape in the artifact: adaptive OOM backoff
    # may have halved the batch, and the gate must not compare a silent
    # batch-8 number as batch-16 evidence
    extra = {"batch": batch, "seq": seq}
    if batch != initial_batch:
        # PJRT peak_bytes_in_use is monotonic across the process, so the
        # HBM high-water below includes the FAILED larger-batch attempt —
        # flag it so the memory-proof datum is not read at face value
        extra["oom_backoff_from_batch"] = initial_batch
    if bench_config:
        # tag smoke runs distinctly: a CPU run under
        # BENCH_CONFIG=llama1b_s4096 measures the tiny model, and must
        # not be filterable as 1B evidence
        extra["config"] = (bench_config if on_tpu
                           else f"smoke_{bench_config}")
    if skip_extras:
        extra["extras_skipped"] = True
    try:
        # HBM high-water (PJRT peak_bytes_in_use): the memory-proof datum
        # for the llama1b_s4096 config; cheap, so reported for every run
        from paddle_tpu import device as _pdev

        hbm_peak = _pdev.max_memory_allocated()
        if hbm_peak:
            extra["hbm_high_water_bytes"] = int(hbm_peak)
            print(f"# HBM high-water: {hbm_peak / 2**30:.2f} GiB",
                  file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# hbm stat failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    emit_lock = threading.Lock()
    emitted = []

    def _emit_once(payload):
        # main thread and watchdog can race near the deadline; exactly
        # ONE JSON line may reach stdout (the driver parses lines)
        with emit_lock:
            if emitted:
                return
            emitted.append(True)
            _emit(payload)

    def _watchdog_fire():
        print("# extras phase overran its budget; emitting what we have",
              file=sys.stderr)
        _emit_once({**headline,
                    **({"extra": dict(extra)} if extra else {}),
                    "error": "extras timed out"})
        sys.stderr.flush()
        os._exit(0)

    # generous: 5 extras, two of which compile full models on TPU — this
    # guards against HANGS (dead tunnel), not slow-but-healthy phases.
    # BENCH_EXTRAS_BUDGET lets the experiment queue afford all five
    # configs through a slow tunnel (driver runs keep the default).
    # Armed BEFORE the per-step-sync pass: the headline is already
    # measured, and a tunnel death must not forfeit it.
    extras_budget = float(os.environ.get(
        "BENCH_EXTRAS_BUDGET",
        (900.0 if on_tpu else 480.0) if not skip_extras else 300.0))
    watchdog = threading.Timer(extras_budget, _watchdog_fire)
    watchdog.daemon = True
    watchdog.start()
    # second timed pass, per-step sync: cross-round comparability with
    # per-step-sync-era artifacts (e.g. 20260731T0316Z); the gate uses
    # this field to align methodologies when comparing across eras
    try:
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = train_step(tokens)
            loss._value.block_until_ready()
        extra["per_step_sync_tokens_per_sec"] = round(
            tokens_per_step * steps / (time.perf_counter() - t0), 1)
    except Exception as e:  # noqa: BLE001
        print(f"# per-step-sync pass failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    if skip_extras:
        # sweep experiments only move the headline; skipping the extras
        # keeps each run ~5 min so a whole flash-block sweep fits inside
        # one tunnel-up window (the flaky tunnel is the scarce resource)
        watchdog.cancel()
        _emit_once({**headline, "extra": dict(extra)})
        print(f"# extras skipped (BENCH_EXTRAS=0); model="
              f"{n_params/1e6:.1f}M batch={batch} seq={seq} "
              f"step_time={dt/steps*1000:.1f}ms backend={backend}",
              file=sys.stderr)
        return
    try:
        moe_tps = _moe_bench(on_tpu)
        extra["moe_tokens_per_sec"] = moe_tps
    except Exception as e:  # noqa: BLE001
        print(f"# moe bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        ov, t_us, j_us = _eager_overhead_us()
        extra["eager_op_overhead_us"] = ov
        print(f"# eager dispatch: tensor={t_us}us/op jnp={j_us}us/op "
              f"overhead={ov}us/op", file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# eager overhead bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra["unet_denoise_ms"] = _unet_bench(on_tpu)
    except Exception as e:  # noqa: BLE001
        print(f"# unet bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra["resnet50_images_per_sec"] = _resnet_bench(on_tpu)
    except Exception as e:  # noqa: BLE001
        print(f"# resnet bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra["bert_dp_tokens_per_sec"] = _bert_dp_bench(on_tpu)
    except Exception as e:  # noqa: BLE001
        print(f"# bert dp bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        serve_tps, serve_extras = _serving_bench(on_tpu)
        extra["serve_llama_tokens_per_sec"] = serve_tps
        extra["serve_llama_tokens_per_sec_per_chip"] = \
            serve_extras["tokens_per_sec_per_chip"]
    except Exception as e:  # noqa: BLE001
        print(f"# serving bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    watchdog.cancel()
    _emit_once({**headline, **({"extra": extra} if extra else {})})
    print(f"# model={n_params/1e6:.1f}M params, batch={batch}, seq={seq}, "
          f"steps={steps}, step_time={dt/steps*1000:.1f}ms, "
          f"loss={float(np.asarray(loss.numpy())):.4f}, "
          f"backend={backend}, device_kind={device_kind}, "
          f"peak={peak and peak/1e12 or 0:.0f}TF", file=sys.stderr)


_ONLY_METRICS = {
    "moe": ("moe_tokens_per_sec", "tokens/s"),
    "unet": ("unet_denoise_ms", "ms"),
    "resnet": ("resnet50_images_per_sec", "images/s"),
    "bert": ("bert_dp_tokens_per_sec", "tokens/s/chip"),
    "serve_llama": ("serve_llama_tokens_per_sec", "tokens/s"),
    "prefix_cache": ("prefix_cache_ttft_speedup", "x"),
    "resilient_train": ("resilient_ckpt_roundtrip_ms", "ms"),
    "elastic_ckpt": ("elastic_ckpt_roundtrip_ms", "ms"),
    "observe_overhead": ("observe_overhead_pct", "%"),
    "mesh_train": ("mesh_train_tokens_per_sec_per_chip", "tokens/s/chip"),
    "overload": ("overload_goodput_ratio", "x"),
    "spec_decode": ("spec_decode_goodput_ratio", "x"),
    "router_replay": ("router_replay_cached_token_ratio", "ratio"),
    "moe_plan": ("moe_plan_comm_kib", "KiB"),
    "dcn_plan": ("dcn_plan_dcn_wire_kib", "KiB"),
    "paged_attn": ("paged_attn_fused_tpot_ms", "ms"),
    "fusion_miner": ("fusion_miner_pred_vs_measured", "x"),
}


def main():
    import os

    if "--retune" in sys.argv[1:] or \
            os.environ.get("BENCH_RETUNE", "") in ("1", "true", "True"):
        # autotune escape hatch: ignore cached tile winners and
        # re-measure once (kernels/autotune reads this env switch, so
        # no paddle_tpu import is needed before the backend probe)
        os.environ["PADDLE_TPU_RETUNE"] = "1"

    only = os.environ.get("BENCH_ONLY", "")
    try:
        run_bench()
        return
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        first_err = f"{type(e).__name__}: {e}"
    if only:
        # a failed BENCH_ONLY artifact must carry ITS metric name, not
        # the llama headline's; no pallas retry either (flash flags are
        # irrelevant to most of these and the probe cycle is expensive)
        metric, unit = _ONLY_METRICS.get(only, (f"bench_only_{only}", "?"))
        _emit({"metric": metric, "value": None, "unit": unit,
               "vs_baseline": None, "error": first_err})
        return
    # One retry with the Pallas kernels disabled: a kernel-lowering
    # regression must cost MFU, not the round's number (the XLA fallback
    # paths are always available).  Skip the retry when the kernels can't
    # have been the cause — backend init never got a device (the retry
    # would just repeat a ~long probe cycle), or the flag was already off.
    init_failure = ("backend init failed" in first_err
                    or "Unable to initialize" in first_err
                    or "grabbed by another process" in first_err)
    flag_was_on = True
    try:
        import paddle_tpu as _pt

        flag_was_on = _pt.get_flags(["FLAGS_use_pallas_kernels"])[
            "FLAGS_use_pallas_kernels"]
    except Exception:  # noqa: BLE001
        pass
    if init_failure or not flag_was_on:
        _emit({
            "metric": "llama_pretrain_tokens_per_sec_per_chip",
            "value": None,
            "unit": "tokens/s/chip",
            "vs_baseline": None,
            "error": first_err,
        })
        return
    print("# retrying with FLAGS_use_pallas_kernels=0", file=sys.stderr)
    try:
        import paddle_tpu as paddle

        paddle.set_flags({"FLAGS_use_pallas_kernels": False})
        run_bench()
        print(f"# NOTE: Pallas path failed ({first_err}); number is the "
              "XLA-fallback path", file=sys.stderr)
        return
    except Exception as e2:  # noqa: BLE001 — always emit the JSON line
        traceback.print_exc(file=sys.stderr)
        _emit({
            "metric": "llama_pretrain_tokens_per_sec_per_chip",
            "value": None,
            "unit": "tokens/s/chip",
            "vs_baseline": None,
            "error": f"pallas: {first_err}; fallback: "
                     f"{type(e2).__name__}: {e2}",
        })
        # exit 0 on purpose: a partial JSON with an error field is more
        # useful to the driver than rc=1 with no number at all.


if __name__ == "__main__":
    main()
